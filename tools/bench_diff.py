"""Noise-aware bench regression gate — diff two bench JSONL rounds.

The trajectory went flat for three rounds (BENCH r03→r05: flash
attention pinned at 43 TFLOP/s) and nothing failed.  This tool makes
that impossible to miss again: it compares the current bench output
against a baseline round, per metric, with median-of-trials collapsing
and a per-metric noise tolerance, and exits non-zero on demand when a
metric *regresses* — or when a metric that is supposed to be moving is
*flat*.

Usage::

    # regression gate vs the newest committed BENCH_all round
    python tools/bench_diff.py current.jsonl --fail-on-regression

    # the flatline catch (r03 vs r05 reproduces the miss):
    python tools/bench_diff.py BENCH_all_r05.json \
        --baseline BENCH_all_r03.json \
        --fail-on-flat long_context_flash_attn_tflops

    # CI schema gate (verify_tier1.sh PERF pass)
    python tools/bench_diff.py smoke.jsonl \
        --baseline tools/bench_golden_cpu.jsonl \
        --check-schema --require-same-metrics

Inputs are bench-line JSONL (``{"metric", "value", "unit",
"vs_baseline", ...}`` — bench.py stdout, ``--metrics-out`` files,
``BENCH_all_r*.json`` artifacts) or a driver wrapper object with a
``"parsed"`` record (``BENCH_r*.json``).  Rules of honesty:

- multiple lines per metric = trials → the MEDIAN is compared;
- ``degenerate: true`` rows (a multi-device config that ran dp=1/tp=1)
  are EXCLUDED from gating — a single-device proxy can neither regress
  nor prove a scale win;
- EXCEPT the trainer's multi-device rows (``train3d_*``, the honest
  replacements for the old degenerate ddp_syncbn/tp_gpt proxies —
  ISSUE 12): ``--check-schema`` REFUSES a degenerate or dp=1/tp=1
  train3d row outright, so the multi-device slot can never quietly
  regress to a single-device proxy again;
- ``value: null`` rows (explicit non-measurements) are excluded but
  reported;
- direction is per metric: ``*_ms`` metrics and ``ms/...`` units are
  lower-is-better, everything else higher-is-better;
- ``--check-schema`` hard-fails drift: contract key order, and the
  degenerate flag must match the dp=/tp= world printed in the unit
  string (a ``dp=1`` row without the flag is a silent proxy; a
  ``dp=8`` row WITH it is hiding a real measurement).

Exit codes: 0 clean, 1 gate failure (regression/flat/schema), 2 usage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONTRACT_KEYS = ("metric", "value", "unit", "vs_baseline")

#: the metric the roadmap's flatline lesson is about — the default
#: --fail-on-flat target
FLAT_DEFAULT = "long_context_flash_attn_tflops"

#: metric prefixes whose rows must be HONEST multi-device shapes: the
#: degenerate-row exclusion does NOT apply — a dp=1/tp=1 run of these
#: is a schema failure, not an excluded row (the train3d rows replaced
#: the degenerate ddp_syncbn/tp_gpt proxies precisely to outlaw this)
HONEST_MULTI_DEVICE_PREFIXES = ("train3d_",)


def _must_be_multi_device(metric: str) -> bool:
    return metric.endswith("_step_ms") and any(
        metric.startswith(p) for p in HONEST_MULTI_DEVICE_PREFIXES
    )


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_records(path: str) -> List[dict]:
    """Bench-schema records from JSONL, a JSON array, or a BENCH_r*
    driver wrapper ({"parsed": {...}})."""
    with open(path) as f:
        text = f.read()
    stripped = text.strip()
    if not stripped:
        return []
    try:
        obj = json.loads(stripped)
    except ValueError:
        obj = None
    if isinstance(obj, list):
        return [r for r in obj if isinstance(r, dict)]
    if isinstance(obj, dict):
        if "metric" in obj:
            return [obj]
        parsed = obj.get("parsed")
        return [parsed] if isinstance(parsed, dict) else []
    records = []
    for line in stripped.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # logs interleaved with metric lines
        if isinstance(rec, dict) and "metric" in rec:
            records.append(rec)
    return records


def default_baseline(root: str = REPO) -> Optional[str]:
    """The newest committed round by the number in its name:
    BENCH_all_r*.json preferred (full batches), BENCH_r*.json
    fallback."""
    def _round(p):
        m = re.search(r"_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1

    for pattern in ("BENCH_all_r*.json", "BENCH_r*.json"):
        paths = sorted(glob.glob(os.path.join(root, pattern)), key=_round)
        if paths:
            return paths[-1]
    return None


# ---------------------------------------------------------------------------
# collapsing + comparison
# ---------------------------------------------------------------------------


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def collapse(records: List[dict]) -> Dict[str, dict]:
    """metric → {value (median of trials), trials, unit, degenerate,
    measured}.  The LAST line's unit/degenerate wins (a re-run appended
    to the same JSONL supersedes)."""
    out: Dict[str, dict] = {}
    for rec in records:
        m = rec.get("metric")
        if not isinstance(m, str):
            continue
        slot = out.setdefault(
            m, {"values": [], "unit": "", "degenerate": False}
        )
        v = rec.get("value")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            slot["values"].append(float(v))
        slot["unit"] = rec.get("unit", "") or slot["unit"]
        slot["degenerate"] = bool(rec.get("degenerate", False))
    for m, slot in out.items():
        vals = slot.pop("values")
        slot["trials"] = len(vals)
        slot["measured"] = bool(vals)
        slot["value"] = _median(vals) if vals else None
    return out


def direction(metric: str, unit: str = "") -> str:
    """"lower" for time-like, inflation-ratio, detection-latency and
    false-positive metrics, else "higher"."""
    if metric.endswith("_ms") or metric.endswith("_s"):
        return "lower"
    if metric.endswith("_inflation"):
        return "lower"
    # canary-gate rows: detection latency in virtual ticks, and the
    # false-verdict count (pinned at 0.0 — any rise past the golden
    # value regresses)
    if metric.endswith("_ticks") or metric.endswith("_false_positive"):
        return "lower"
    if (unit or "").strip().startswith("ms"):
        return "lower"
    return "higher"


def compare(
    current: Dict[str, dict],
    baseline: Dict[str, dict],
    *,
    tolerance: float = 0.05,
    tolerances: Optional[Dict[str, float]] = None,
    flat_tolerance: float = 0.01,
) -> List[dict]:
    """Row per metric (union of both rounds) with a status:

    ``regressed`` / ``improved`` / ``ok`` (within noise, moving) /
    ``flat`` (within ``flat_tolerance`` — indistinguishable from the
    baseline) / ``degenerate`` (excluded) / ``not-measured`` (null
    value) / ``new`` / ``missing``.
    """
    tolerances = tolerances or {}
    rows = []
    for metric in sorted(set(current) | set(baseline)):
        cur, base = current.get(metric), baseline.get(metric)
        row = {"metric": metric}
        if cur is None:
            rows.append({**row, "status": "missing",
                         "baseline": base["value"]})
            continue
        if base is None:
            rows.append({**row, "status": "new", "current": cur["value"]})
            continue
        row.update(current=cur["value"], baseline=base["value"],
                   trials=cur["trials"])
        if cur["degenerate"] or base["degenerate"]:
            rows.append({**row, "status": "degenerate"})
            continue
        if not cur["measured"] or not base["measured"]:
            rows.append({**row, "status": "not-measured"})
            continue
        tol = tolerances.get(metric, tolerance)
        denom = abs(base["value"]) or 1e-12
        rel = (cur["value"] - base["value"]) / denom
        row["delta"] = rel
        if direction(metric, cur["unit"]) == "lower":
            rel = -rel  # improvement = smaller
        if abs(row["delta"]) <= flat_tolerance:
            status = "flat"
        elif rel < -tol:
            status = "regressed"
        elif rel > tol:
            status = "improved"
        else:
            status = "ok"
        rows.append({**row, "status": status})
    return rows


# ---------------------------------------------------------------------------
# schema check
# ---------------------------------------------------------------------------

_WORLD_RE = re.compile(r"\b(dp|tp)=(\d+)\b")


def check_schema(records: List[dict]) -> List[str]:
    """Contract-drift findings (empty = clean).  Checks: the four
    contract keys lead every record in order; metric/unit types; the
    degenerate flag is HONEST against the dp=/tp= world the unit
    string records."""
    problems = []
    if not records:
        return ["no bench records found"]
    for i, rec in enumerate(records):
        where = f"line {i + 1} ({rec.get('metric', '?')})"
        if list(rec)[:4] != list(CONTRACT_KEYS):
            problems.append(
                f"{where}: keys {list(rec)[:4]} != contract "
                f"{list(CONTRACT_KEYS)}"
            )
            continue
        if not isinstance(rec["metric"], str) or not rec["metric"]:
            problems.append(f"{where}: empty metric name")
        v = rec["value"]
        if v is not None and (isinstance(v, bool)
                              or not isinstance(v, (int, float))):
            problems.append(f"{where}: value {v!r} is not a number/null")
        if not isinstance(rec["unit"], str):
            problems.append(f"{where}: unit is not a string")
        worlds = dict(_WORLD_RE.findall(rec.get("unit", "") or ""))
        flagged = bool(rec.get("degenerate", False))
        if _must_be_multi_device(rec.get("metric", "")):
            collapsed = not worlds or all(
                int(n) == 1 for n in worlds.values()
            )
            if flagged or collapsed:
                problems.append(
                    f"{where}: train3d rows must be honest multi-device "
                    f"shapes (dp/tp >= 2, never degenerate); unit says "
                    f"{worlds or 'no world'}, degenerate={flagged} — "
                    "run on a real (or mocked 8-device) mesh"
                )
                continue
        if worlds:
            collapsed = all(int(n) == 1 for n in worlds.values())
            if collapsed and not flagged:
                problems.append(
                    f"{where}: unit says {worlds} (single-device proxy) "
                    "but the row is not marked degenerate"
                )
            if not collapsed and flagged:
                problems.append(
                    f"{where}: marked degenerate but unit says {worlds} "
                    "(a real multi-device measurement)"
                )
        elif flagged:
            problems.append(
                f"{where}: marked degenerate but the unit string records "
                "no dp=/tp= world to justify it"
            )
    return problems


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def render(rows: List[dict]) -> str:
    out = [f"{'metric':<38} {'baseline':>12} {'current':>12} "
           f"{'delta':>8}  status"]
    for r in rows:
        base = r.get("baseline")
        cur = r.get("current")
        delta = r.get("delta")
        out.append(
            f"{r['metric']:<38} "
            f"{base if base is not None else '-':>12} "
            f"{cur if cur is not None else '-':>12} "
            f"{f'{100 * delta:+.1f}%' if delta is not None else '-':>8}"
            f"  {r['status']}"
        )
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="noise-aware bench regression/flatline gate "
        "(docs/observability.md)"
    )
    ap.add_argument("current", help="bench JSONL / BENCH_*.json to judge")
    ap.add_argument("--baseline", default=None,
                    help="round to compare against (default: the newest "
                    "BENCH_all_r*.json at the repo root)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="relative noise tolerance (default 0.05)")
    ap.add_argument("--tol", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="per-metric tolerance override (repeatable)")
    ap.add_argument("--flat-tolerance", type=float, default=0.01,
                    help="|delta| at or under this is 'flat' "
                    "(default 0.01)")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 if any gated metric regressed past "
                    "tolerance")
    ap.add_argument("--fail-on-flat", nargs="?", const=FLAT_DEFAULT,
                    default=None, metavar="METRICS",
                    help="exit 1 if these comma-separated metrics are "
                    f"flat vs baseline (bare flag: {FLAT_DEFAULT} — "
                    "the r03→r05 lesson); a metric missing from the "
                    "current round also fails")
    ap.add_argument("--check-schema", action="store_true",
                    help="hard-fail contract drift in CURRENT (key "
                    "order, degenerate honesty vs the unit's dp=/tp=)")
    ap.add_argument("--require-same-metrics", action="store_true",
                    help="fail when CURRENT's metric set differs from "
                    "the baseline's (CI golden-line mode)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the comparison rows as one JSON object")
    args = ap.parse_args(argv)

    baseline_path = args.baseline or default_baseline()
    if baseline_path is None:
        ap.error("no --baseline given and no BENCH_*round artifacts found")
    cur_records = load_records(args.current)
    base_records = load_records(baseline_path)
    tolerances = {}
    for spec in args.tol:
        if "=" not in spec:
            ap.error(f"--tol wants METRIC=FRAC, got {spec!r}")
        k, v = spec.split("=", 1)
        tolerances[k] = float(v)

    failures: List[str] = []
    if args.check_schema:
        for p in check_schema(cur_records):
            failures.append(f"schema: {p}")

    current = collapse(cur_records)
    baseline = collapse(base_records)
    rows = compare(
        current, baseline, tolerance=args.tolerance,
        tolerances=tolerances, flat_tolerance=args.flat_tolerance,
    )
    print(f"baseline: {baseline_path}")
    print(render(rows))

    if args.require_same_metrics and set(current) != set(baseline):
        failures.append(
            f"metric set drift: only-current="
            f"{sorted(set(current) - set(baseline))} only-baseline="
            f"{sorted(set(baseline) - set(current))}"
        )
    by_metric = {r["metric"]: r for r in rows}
    if args.fail_on_regression:
        for r in rows:
            if r["status"] == "regressed":
                failures.append(
                    f"regression: {r['metric']} "
                    f"{r['baseline']} -> {r['current']} "
                    f"({100 * r['delta']:+.1f}%)"
                )
    if args.fail_on_flat:
        for metric in args.fail_on_flat.split(","):
            metric = metric.strip()
            r = by_metric.get(metric)
            if r is None or r["status"] in ("missing", "not-measured"):
                failures.append(
                    f"flatline gate: {metric} not measured this round"
                )
            elif r["status"] == "flat":
                failures.append(
                    f"flatline: {metric} stuck at {r['current']} "
                    f"(baseline {r['baseline']}, "
                    f"|delta| <= {args.flat_tolerance:.0%})"
                )
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"baseline": baseline_path, "rows": rows,
                       "failures": failures}, f, indent=2)
            f.write("\n")
    for f_ in failures:
        print(f"FAIL {f_}", file=sys.stderr)
    if failures:
        return 1
    print("bench_diff: OK "
          f"({sum(1 for r in rows if r['status'] == 'degenerate')} "
          "degenerate rows excluded)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
