"""Chaos-storm goodput drill — the GOODPUT acceptance gate's engine.

Drives the resilient example's REAL training program
(``examples/simple/resilient/train_resilient.py::build_training`` — the
same compiled steps the OBS/FLIGHT/LINT gates audit) through an
``APEX_TPU_CHAOS``-style preemption storm, fed by the goodput
subsystem's resumable stream (``apex_tpu.goodput.ResumableStream`` over
a synthetic token corpus), checkpointed by the zero-stall async engine,
and proves the three headline numbers (docs/goodput.md):

1. **goodput >= 99%** — the :class:`GoodputAccountant` ledger across
   every relaunch of the storm (preempt every ``--preempt-every``
   steps, plus one save I/O fault that must heal on retry);
2. **bit-exact resume** — the stormed run's per-step loss sequence
   equals an uninterrupted reference run's, bit for bit (the stream
   cursor rides inside every checkpoint and is verified on restore);
3. **checkpoint stall < 1%** — the step path's snapshot+enqueue time
   over wall time (``goodput/ckpt/stall_frac``), watched live by
   :func:`apex_tpu.observability.goodput_rules` (zero pages on a
   healthy storm).

It then plants the two on-disk shapes of a mid-write death — orbax tmp
debris AND a digit-named half-written step dir newer than every
complete step — and proves the previous checkpoint stays the resume
anchor (``latest_step`` ignores both; a relaunch resumes from it).

``--json`` writes the full evidence artifact; ``bench.py --config
goodput`` reuses :func:`run_drill` for its golden-pinned rows.

Usage::

    python tools/goodput_drill.py --steps 60 --preempt-every 12 \
        --json /tmp/goodput_drill.json
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_example():
    path = os.path.join(
        REPO, "examples", "simple", "resilient", "train_resilient.py"
    )
    spec = importlib.util.spec_from_file_location("train_resilient", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _make_stream(workdir, rows, seed=17, prefetch=2):
    """A resumable stream over a synthetic token corpus sized so the
    drill crosses epoch boundaries (the seek math's hard case)."""
    from apex_tpu.data import (
        DataLoader,
        TokenFileDataset,
        synthetic_token_corpus,
    )
    from apex_tpu.goodput import ResumableStream

    corpus = synthetic_token_corpus(
        os.path.join(workdir, "drill_corpus.bin"),
        vocab_size=4096, num_tokens=rows * 12 * 24, seed=seed,
    )
    ds = TokenFileDataset(corpus, seq_len=12)
    loader = DataLoader(ds, batch_size=rows, seed=seed)
    return ResumableStream(loader, prefetch=prefetch), loader


def run_drill(
    steps: int = 60,
    preempt_every: int = 12,
    save_every: int = 8,
    step_floor_ms: float = 75.0,
    workdir: str = "/tmp/apex_tpu_goodput_drill",
) -> dict:
    """Run the reference + storm pair and return the evidence dict.

    ``step_floor_ms`` floors each step's wall time so the CPU toy step
    stands in for a realistic device step — the <1% stall bound is a
    claim about checkpoint overhead relative to real step time, and a
    microsecond toy step would turn it into a claim about nothing.
    The 75ms default is a mid-size-model device step, chosen with
    CI headroom in mind: on an oversubscribed runner the snapshot's
    sub-ms cost inflates severalfold from scheduler/GIL contention
    (observed ~4ms/save under 3x CPU oversubscription), and the bound
    must reflect the engine's overhead, not the runner's weather.
    """
    import shutil

    mod = _load_example()
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)

    # The drill compresses production cadence ~1000x: saves land every
    # few hundred ms of floored toy steps, but the write behind them
    # costs whatever this runner's disk costs TODAY (observed 0.1s
    # quiet, >1s under CI load).  At the default queue depth a loaded
    # disk fills the queue and save()'s enqueue blocks — and the stall
    # fraction stops measuring the engine's step-path cost (the
    # snapshot, the <1% claim) and starts measuring disk weather.
    # Size the queue to absorb every save of one invocation; bounded
    # backpressure itself is pinned by the unit tier
    # (tests/test_goodput.py), not by this gate.
    saves_per_invocation = -(-steps // save_every) + 2
    prior_depth = os.environ.get("APEX_TPU_CKPT_QUEUE")
    os.environ["APEX_TPU_CKPT_QUEUE"] = str(
        max(8, saves_per_invocation)
    )
    try:
        return _run_drill_inner(
            mod, steps, preempt_every, save_every, step_floor_ms,
            workdir,
        )
    finally:
        if prior_depth is None:
            os.environ.pop("APEX_TPU_CKPT_QUEUE", None)
        else:
            os.environ["APEX_TPU_CKPT_QUEUE"] = prior_depth


def _run_drill_inner(
    mod, steps, preempt_every, save_every, step_floor_ms, workdir,
) -> dict:
    import numpy as np
    import jax.numpy as jnp

    from apex_tpu import checkpoint as ckpt
    from apex_tpu import observability as obs
    from apex_tpu.goodput import verify_stream_state
    from apex_tpu.observability.metrics import board
    from apex_tpu.observability.spans import SpanRecorder
    from apex_tpu.resilience import ObserverFanout, chaos, run_resilient

    t = mod.build_training(accum=1, wire="f32", fetch_every=8)
    rows, registry = t["rows"], t["registry"]
    compute_grads, apply_update = t["compute_grads"], t["apply_update"]
    rs = np.random.RandomState(3)
    w_true = jnp.asarray(rs.randn(8, 4), jnp.float32)

    def make_batch(toks):
        x = jnp.asarray(
            toks[:, :8].astype(np.float32) / 4096.0 - 0.5, jnp.float32
        )
        return (
            x.reshape(1, rows, 8),
            (x @ w_true).reshape(1, rows, 4),
        )

    def run(directory, stream, *, faults=(), losses=None, observers=(),
            spans=None, num_steps=steps, acct=None):
        """run_resilient in a relaunch loop (each preemption = one
        process death + restart), accumulating one ledger."""
        cur = {"step": -1}

        def batch_fn(step):
            cur["step"] = step
            return make_batch(stream(step))

        def step_fn(state, batch):
            t0 = time.monotonic()
            inner = state["train"]
            loss, scaled = compute_grads(
                inner["params"], inner["scaler"], batch
            )
            scaled = chaos.corrupt_tree(scaled, cur["step"])
            new_inner, verdict = apply_update(scaled, inner, loss)
            registry.observe(cur["step"], new_inner["metrics"])
            if losses is not None:
                losses[cur["step"]] = float(loss)
            if step_floor_ms > 0:  # emulate a realistic device step
                rest = step_floor_ms / 1e3 - (time.monotonic() - t0)
                if rest > 0:
                    time.sleep(rest)
            return (
                {"train": new_inner,
                 "stream": stream.state(cur["step"] + 1)},
                {"skipped": verdict.skipped},
            )

        init = {"train": t["state"], "stream": stream.state(0)}
        acct = acct if acct is not None else obs.GoodputAccountant()
        ledger = {"invocations": 0, "saves": 0.0, "writes": 0.0,
                  "failures": 0.0, "max_stall_frac": 0.0,
                  "snapshot_ms": [], "write_ms": [], "finalize_ms": []}

        class PhaseCollector:
            def on_checkpoint(self, step, info=None):
                if info is None:
                    return
                if info.get("phase") == "finalize":
                    ledger["finalize_ms"].append(
                        (info["t1"] - info["t0"]) * 1e3
                    )
                    return
                if info.get("phase") != "write":
                    return
                ledger["write_ms"].append((info["t1"] - info["t0"]) * 1e3)
                if info.get("snapshot_t1") is not None:
                    # the full step-path cost of this save: snapshot
                    # plus the enqueue wait (nonzero when the bounded
                    # queue backpressures) — what the
                    # goodput_ckpt_enqueue_ms bench row claims
                    ledger["snapshot_ms"].append(
                        (info["snapshot_t1"] - info["snapshot_t0"]) * 1e3
                        + info.get("enqueue_ms", 0.0)
                    )

        # spans joins the fan-out inside run_resilient itself (the
        # spans= argument) — adding it here would double-record
        fanout = ObserverFanout([acct, PhaseCollector(), *observers])
        with chaos.inject(*faults):
            while True:
                res = run_resilient(
                    step_fn, init, batch_fn, directory=directory,
                    num_steps=num_steps, save_interval_steps=save_every,
                    max_to_keep=3, rollback_after=5,
                    observer=fanout, spans=spans,
                )
                ledger["invocations"] += 1
                for key in ("saves", "writes", "failures"):
                    ledger[key] += board.get(f"goodput/ckpt/{key}", 0.0)
                ledger["max_stall_frac"] = max(
                    ledger["max_stall_frac"],
                    board.get("goodput/ckpt/stall_frac", 0.0),
                )
                if not res.preempted:
                    return res, acct, ledger

    # -- 0. warm the checkpoint path ---------------------------------------
    # the first orbax save of a process pays one-time setup (event
    # loops, type registries, handler caches — ~1s on CPU); in
    # production it amortizes over hours, in a 1-2s drill it would
    # dominate the stall fraction.  One throwaway save measures the
    # engine at steady state.
    from apex_tpu.goodput import AsyncCheckpointEngine

    with AsyncCheckpointEngine(os.path.join(workdir, "warmup")) as warm:
        warm.save(0, {"w": np.zeros((4,), np.float32)})
        warm.wait_until_finished()

    # -- 1. uninterrupted reference ----------------------------------------
    # also the cleanest overhead measurement: a full-length run whose
    # only checkpoint cost is the step-path snapshot (the storm's
    # per-invocation windows are too short to judge a fraction on)
    losses_ref: dict = {}
    ref_stream, _ = _make_stream(workdir, rows)
    ref_res, _, ref_ledger = run(
        os.path.join(workdir, "ref"), ref_stream, losses=losses_ref
    )
    ref_stream.close()

    # -- 2. the storm ------------------------------------------------------
    # the APEX_TPU_CHAOS spec, built through the same parser real runs
    # use: preempt every N steps, plus ONE save I/O fault that must
    # heal on retry (the accountant's retry column proves it fired)
    preempts = ",".join(
        str(s) for s in range(preempt_every, steps, preempt_every)
    )
    spec = f"preemption@{preempts};checkpoint_save:raise:x1@{save_every}"
    faults, seed = chaos.parse_spec(spec)

    losses_storm: dict = {}
    storm_dir = os.path.join(workdir, "storm")
    storm_stream, storm_loader = _make_stream(workdir, rows)
    spans = SpanRecorder(8192, directory=os.path.join(workdir, "spans"))
    pages: list = []
    acct = obs.GoodputAccountant()
    watchdog = obs.Watchdog(
        # ckpt_stall watches the LIVE short-window fraction, which on
        # a loaded CI runner jitters with scheduler spikes the
        # full-run acceptance number (ckpt.stall_frac, asserted <1%)
        # averages out — a 5% live budget keeps the zero-pages
        # assertion about the engine, not the runner's weather, while
        # still catching a writer that genuinely falls behind
        obs.goodput_rules(floor=0.99, ckpt_stall={"max_fraction": 0.05}),
        registry=registry, goodput=acct,
        on_unhealthy=pages.append, check_every=4,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)  # the healed retry
        storm_res, acct, ledger = run(
            storm_dir, storm_stream,
            faults=faults, losses=losses_storm,
            observers=[watchdog], spans=spans, acct=acct,
        )

    # bit-exactness: every step of the storm equals the reference
    drift = max(
        (abs(losses_storm[s] - losses_ref[s]) for s in losses_ref),
        default=float("inf"),
    ) if set(losses_storm) == set(losses_ref) else float("inf")

    # the stream cursor inside the NEWEST checkpoint, verified against
    # the loader it indexes (the final steps past the last interval
    # are re-run on resume — the cursor must point exactly there)
    last_saved = ckpt.latest_step(storm_dir)
    restored = ckpt.restore_step_dir(storm_dir, last_saved)
    cursor = verify_stream_state(storm_loader, restored["stream"])
    storm_stream.close()

    span_names = [s["name"] for s in spans.snapshot()]

    # -- 3. the planted mid-write kill -------------------------------------
    latest_before = ckpt.latest_step(storm_dir)
    # shape A: orbax tmp debris (died before the commit rename)
    debris = os.path.join(
        storm_dir, f"{latest_before + 1}.orbax-checkpoint-tmp-drill"
    )
    os.makedirs(debris, exist_ok=True)
    with open(os.path.join(debris, "params"), "w") as f:
        f.write("torn write\n")
    # shape B: a digit-named dir with payload but NO commit marker
    # (non-atomic fs / torn non-orbax write) — newer than everything
    half = os.path.join(storm_dir, str(latest_before + 2))
    os.makedirs(half, exist_ok=True)
    with open(os.path.join(half, "params"), "w") as f:
        f.write("half-written payload\n")
    latest_after = ckpt.latest_step(storm_dir)
    # a relaunch must resume from the intact previous checkpoint
    resume_stream, _ = _make_stream(workdir, rows)
    resume_res, _, _ = run(
        storm_dir, resume_stream, num_steps=steps,
    )
    resume_stream.close()

    return {
        "steps": steps,
        "preempt_every": preempt_every,
        "save_every": save_every,
        "chaos_spec": spec,
        "goodput": acct.goodput(),
        "accountant": acct.snapshot(),
        "invocations": ledger["invocations"],
        "ckpt": {
            "saves": ledger["saves"] + ref_ledger["saves"],
            "writes": ledger["writes"] + ref_ledger["writes"],
            "failures": ledger["failures"],
            # the <1% overhead claim, measured on the full-length
            # uninterrupted run: the storm's per-invocation windows
            # (preempt_every steps, barely past the engine's minimum
            # stall window) are too short to judge a fraction on — one
            # scheduler-starved snapshot on a loaded CI box reads as
            # multiple percent there while the same spike is noise
            # over the full run.  The storm max stays as telemetry.
            "stall_frac": ref_ledger["max_stall_frac"],
            "storm_max_stall_frac": ledger["max_stall_frac"],
            "snapshot_ms": sorted(
                ref_ledger["snapshot_ms"] + ledger["snapshot_ms"]
            ),
            "write_ms": sorted(
                ref_ledger["write_ms"] + ledger["write_ms"]
            ),
            "finalize_ms": sorted(
                ref_ledger["finalize_ms"] + ledger["finalize_ms"]
            ),
        },
        "input_stall_fraction": board.get(
            "data/input_stall_fraction", 0.0
        ),
        "loss_trajectory": {
            "ref_steps": len(losses_ref),
            "storm_steps": len(losses_storm),
            "max_abs_drift": drift,
            "bit_exact": drift == 0.0,
            "final_loss": losses_ref.get(steps - 1),
        },
        "stream_cursor": {
            "restored_next_batch": cursor,
            "expected": last_saved + 1,
        },
        "spans": {
            "ckpt_snapshot": span_names.count("ckpt/snapshot"),
            "ckpt_write": span_names.count("ckpt/write"),
            "ckpt_finalize": span_names.count("ckpt/finalize"),
            "train_step": span_names.count("train/step"),
        },
        "watchdog_pages": [
            {"rule": e.rule, "severity": e.severity, "message": e.message}
            for e in pages
        ],
        "planted_midwrite": {
            "latest_before": latest_before,
            "latest_after_plant": latest_after,
            "previous_intact": latest_after == latest_before,
            "resumed_from": resume_res.resumed_from,
            "resume_ok": resume_res.resumed_from == latest_before,
        },
        # the runtime lock-order sanitizer's census (docs/analysis.md
        # "Concurrency & replay-purity passes"): under
        # APEX_TPU_LOCKSAN=1 every TrackedLock acquisition in the
        # drill — the async engine's writer thread racing the step
        # path is the real workload — lands in the graph; the GOODPUT
        # gate asserts armed + zero cycles + a non-empty census
        "locksan": obs.sanitizer_report(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
    )
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--preempt-every", type=int, default=12)
    ap.add_argument("--save-every", type=int, default=8)
    ap.add_argument("--step-floor-ms", type=float, default=75.0)
    ap.add_argument("--dir", default="/tmp/apex_tpu_goodput_drill")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="write the evidence artifact")
    ap.add_argument("--floor", type=float, default=0.99,
                    help="goodput acceptance floor")
    ap.add_argument("--max-stall", type=float, default=0.01,
                    help="checkpoint stall-fraction acceptance bound")
    args = ap.parse_args(argv)

    art = run_drill(
        steps=args.steps, preempt_every=args.preempt_every,
        save_every=args.save_every, step_floor_ms=args.step_floor_ms,
        workdir=args.dir,
    )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(art, f, indent=1)

    print(
        "goodput drill: goodput=%.4f (accepted=%d skipped=%d "
        "discarded=%d retries=%d resumes=%d over %d invocations)"
        % (art["goodput"], art["accountant"]["accepted"],
           art["accountant"]["skipped"], art["accountant"]["discarded"],
           art["accountant"]["retries"], art["accountant"]["resumes"],
           art["invocations"])
    )
    print(
        "  ckpt: saves=%d writes=%d stall_frac=%.5f  spans: "
        "snapshot=%d write=%d finalize=%d"
        % (art["ckpt"]["saves"], art["ckpt"]["writes"],
           art["ckpt"]["stall_frac"], art["spans"]["ckpt_snapshot"],
           art["spans"]["ckpt_write"], art["spans"]["ckpt_finalize"])
    )
    print(
        "  resume: bit_exact=%s cursor=%s planted_midwrite intact=%s "
        "resume_ok=%s watchdog_pages=%d"
        % (art["loss_trajectory"]["bit_exact"],
           art["stream_cursor"]["restored_next_batch"],
           art["planted_midwrite"]["previous_intact"],
           art["planted_midwrite"]["resume_ok"],
           len(art["watchdog_pages"]))
    )

    failures = []
    if art["goodput"] < args.floor:
        failures.append(
            f"goodput {art['goodput']:.4f} under floor {args.floor}"
        )
    if not art["loss_trajectory"]["bit_exact"]:
        failures.append(
            "resumed loss trajectory drifted: max_abs_drift="
            f"{art['loss_trajectory']['max_abs_drift']}"
        )
    if art["ckpt"]["stall_frac"] >= args.max_stall:
        failures.append(
            f"ckpt stall {art['ckpt']['stall_frac']:.5f} >= "
            f"{args.max_stall}"
        )
    if not art["planted_midwrite"]["previous_intact"]:
        failures.append("planted mid-write debris changed latest_step")
    if not art["planted_midwrite"]["resume_ok"]:
        failures.append("relaunch did not resume from the intact step")
    if art["stream_cursor"]["restored_next_batch"] != \
            art["stream_cursor"]["expected"]:
        failures.append("checkpointed stream cursor off")
    if art["spans"]["ckpt_write"] == 0 or art["spans"]["ckpt_snapshot"] == 0:
        failures.append("no ckpt spans on the timeline")
    if art["watchdog_pages"]:
        failures.append(f"watchdog paged: {art['watchdog_pages']}")
    if art["locksan"]["armed"] and art["locksan"]["cycles"]:
        failures.append(
            "lock-order cycles under LOCKSAN: "
            f"{art['locksan']['cycles']}"
        )
    for f_ in failures:
        print(f"GOODPUT DRILL FAIL: {f_}", file=sys.stderr)
    if not failures:
        print("goodput drill: PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
