"""Shard report — the human-readable sharding plan + memory breakdown
of a compiled step program.

Where ``tools/graph_lint.py`` answers "is the plan violated?" with
findings and an exit code, this renders the plan itself: one row per
ENTRY parameter (its compiled GSPMD sharding, global bytes, declared
PartitionSpec and conformance verdict), the per-mesh-axis collective
schedule, and the static peak-HBM estimate with top-K per-buffer
attribution (``apex_tpu.analysis.memory`` — the live-range model of
docs/analysis.md "Sharding & memory passes").

Targets (same build paths as graph_lint, so the report describes the
EXACT programs the examples dispatch):

  --target resilient   examples/simple/resilient train step (both
                       jitted programs), against its own declared
                       rule table and DDP collective plan.  Run under
                       XLA_FLAGS=--xla_force_host_platform_device_count=8
                       to see a real dp mesh (the verify_tier1.sh gate
                       does).
  --target serve       the serve example's AOT prefill/decode programs
                       (KV page pool budgeted from its static shape).
  --target train       the composable trainer's demo config
                       (apex_tpu.train.build_demo) at --dp x --tp,
                       against the trainer's OWN derived rule table and
                       collective plan — the verify_tier1.sh TRAIN gate
                       renders this on a mocked 8-device mesh.
  --hlo FILE           any optimized-HLO text dump.

Options:

  --budget BYTES       peak-HBM budget: the report prints headroom and
                       the exit code turns 1 when exceeded (the same
                       memory-budget gate graph_lint enforces)
  --top K              buffers to attribute at the peak (default 10)
  --wire / --accum     forwarded to the target builders
  --json FILE          machine artifact: the full lint report plus
                       peak_hbm_bytes / peak_hbm_by_program /
                       peak_hbm_by_category / shard_plan sections
                       (the CI schema verify_tier1.sh checks)

Exit code: 0 clean, 1 any ERROR finding (incl. budget overflow),
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover - fallthrough


def _table(rows, headers):
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows
        else len(str(h))
        for i, h in enumerate(headers)
    ]
    line = "  ".join(str(h).ljust(w) for h, w in zip(headers, widths))
    out = [line, "-" * len(line)]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def render(report, top: int, budget=None) -> str:
    """Text report from a lint Report whose sections were filled by
    ``analysis.attach_shard_sections`` (plus per-program collective
    schedules re-read from the kept HLO)."""
    from apex_tpu.analysis import hlo as hlo_lib
    from apex_tpu.analysis import memory as mem

    sec = report.sections
    out = [f"shard report: {report.target}"]

    plan = sec.get("shard_plan") or []
    if plan:
        out.append("\n== parameter shard plan "
                   "(compiled sharding vs declared spec)")
        out.append(_table(
            [
                (
                    r["program"].rsplit("/", 1)[-1], r["name"],
                    r["shape"], _fmt_bytes(r["global_bytes"]),
                    r["sharding"], r["intended"] or "-", r["verdict"],
                )
                for r in plan
            ],
            ("program", "param", "local shape", "global",
             "compiled sharding", "declared", "verdict"),
        ))

    for prog_name, text in getattr(report, "programs", []):
        if not text:
            continue
        colls = hlo_lib.collective_instructions(text)
        if colls:
            out.append(f"\n== collective schedule ({prog_name})")
            out.append(_table(
                [
                    (
                        c["kind"], c["group_size"] or "-",
                        _fmt_bytes(c["bytes"]),
                        "/".join(sorted(c["dtypes"])) or "-",
                        (c["op_name"] or c["name"])[-60:],
                    )
                    for c in colls
                ],
                ("kind", "group", "bytes", "dtypes", "op"),
            ))
        est = mem.estimate_peak(text, top_k=top)
        out.append(
            f"\n== memory ({prog_name}): static peak "
            f"{_fmt_bytes(est['peak_bytes'])} at instruction "
            f"#{est['peak_index']}"
        )
        cats = ", ".join(
            f"{k}={_fmt_bytes(v)}"
            for k, v in sorted(
                est["by_category"].items(), key=lambda kv: -kv[1]
            )
        )
        out.append(f"   at-peak by category: {cats}")
        out.append(_table(
            [
                (
                    b["category"], b["name"], _fmt_bytes(b["bytes"]),
                    f"[{b['defined']}, {b['freed']}]",
                    (b["op_name"] or "")[-50:],
                )
                for b in est["buffers"]
            ],
            ("category", "buffer", "bytes", "live", "op"),
        ))

    peak = sec.get("peak_hbm_bytes", 0)
    if budget is not None:
        headroom = budget - peak
        verdict = "WITHIN" if headroom >= 0 else "EXCEEDS"
        out.append(
            f"\nbudget: peak {_fmt_bytes(peak)} {verdict} "
            f"{_fmt_bytes(budget)} "
            f"(headroom {_fmt_bytes(headroom)})"
        )
    if report.findings:
        out.append("\n== findings")
        for f in report.findings:
            out.append("  " + f.render())
    else:
        out.append("\nfindings: none — the declared plan holds")
    return "\n".join(out)


def main():
    ap = argparse.ArgumentParser(
        description="human-readable shard plan + memory breakdown "
        "(docs/analysis.md 'Sharding & memory passes')"
    )
    ap.add_argument("--target", choices=["resilient", "serve", "train"],
                    default=None)
    ap.add_argument("--hlo", metavar="FILE", default=None)
    ap.add_argument("--wire", default="f32",
                    choices=["f32", "bf16", "int8"])
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--dp", type=int, default=2,
                    help="train-target dp axis size (default 2)")
    ap.add_argument("--tp", type=int, default=2,
                    help="train-target tp axis size (default 2)")
    ap.add_argument("--budget", type=int, default=None, metavar="BYTES")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--json", metavar="FILE", default=None)
    ap.add_argument("--donated", type=int, default=None,
                    help="declared donated-leaf count (--hlo mode)")
    ap.add_argument("--expect", type=json.loads, default=None,
                    metavar="JSON", help="collective expectations "
                    "(forwarded to graph_lint's resilient target)")
    args = ap.parse_args()

    if bool(args.target) == bool(args.hlo):
        ap.error("exactly one of --target / --hlo is required")

    # reuse graph_lint's builders so THIS report and the CI gate can
    # never describe different programs
    try:
        import graph_lint as gl  # python tools/shard_report.py
    except ImportError:  # imported as tools.shard_report
        from tools import graph_lint as gl

    if args.hlo:
        report = gl.lint_hlo_file(args)
    elif args.target == "serve":
        report = gl.lint_serve(args)
    elif args.target == "train":
        report = gl.lint_train(args)
    else:
        report = gl.lint_resilient(args)

    from apex_tpu import analysis

    analysis.publish_report(report)

    print(render(report, top=args.top, budget=args.budget))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report.to_json(), f, indent=2)
            f.write("\n")
        print(f"[shard_report] wrote {args.json}", file=sys.stderr)
    return 0 if report.ok() else 1


if __name__ == "__main__":
    sys.exit(main())
