"""Graph lint CLI — run the apex_tpu.analysis passes over a step
program and emit findings as text + a BENCH-style JSON artifact.

Targets:

  --target resilient   Build the resilient example's ACTUAL training
                       step (examples/simple/resilient/train_resilient
                       .py::build_training — the same compiled programs
                       the example dispatches) and lint both jitted
                       functions: compute_grads and apply_update.
                       This is the tools/verify_tier1.sh gate: any
                       ERROR finding fails CI.

  --target serve       Build the serve example's ACTUAL engine
                       (examples/simple/serve/serve_gpt.py::
                       build_serving) and lint its AOT step programs —
                       the smallest prefill bucket and the decode step
                       (transfer-free + donation-aliased: the paged KV
                       pool must update in place).  The
                       verify_tier1.sh SERVE gate.  --wire selects the
                       KV wire format here.

  --target train       Build the composable trainer's demo config
                       (apex_tpu.train.build_demo — the exact program
                       bench.py --config train3d times) at --dp x --tp
                       and lint the fused step against the trainer's
                       OWN derived rule table, collective plan, and
                       --budget.  Run under XLA_FLAGS=
                       --xla_force_host_platform_device_count=8 for a
                       real mesh (the verify_tier1.sh TRAIN gate does).

  --hlo FILE           Lint an optimized-HLO text dump (e.g. bench.py
                       --hlo-out) with the HLO-level passes only.

Both targets also run the sharding & memory passes by default
(docs/analysis.md "Sharding & memory passes"): spec conformance and
the no-unplanned-resharding check against the target's own declared
plan, and — with --budget — the static peak-HBM gate.  The --json
artifact carries peak_hbm_bytes / peak_hbm_by_program /
peak_hbm_by_category and the per-parameter shard_plan table next to
the findings (tools/shard_report.py renders the same sections
human-readably).

Options:

  --wire / --accum     resilient-target knobs (forwarded to
                       build_training, docs/comm.md)
  --budget BYTES       static peak-HBM budget (memory-budget ERROR
                       when the estimate exceeds it)
  --expect JSON        collective expectations, e.g.
                       '{"all-to-all": {"count": 2, "dtypes": ["s8",
                       "f32"]}}' (schema: analysis.passes
                       .collective_pass)
  --donated N          declared donated-leaf count for --hlo mode
  --json FILE          write the full report as one JSON object
  --fail-on LEVEL      exit 1 at this severity (default error)

Exit code: 0 clean at --fail-on, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_resilient_module():
    """Import the example script as a module (it lives outside the
    package tree on purpose — it is user-facing sample code)."""
    import importlib.util

    path = os.path.join(
        REPO, "examples", "simple", "resilient", "train_resilient.py"
    )
    spec = importlib.util.spec_from_file_location("train_resilient", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def lint_resilient(args):
    """Check the resilient example's two jitted step functions.

    ``compute_grads`` is traced on a real batch from the example's own
    ``batch_fn``; ``apply_update`` on the abstract output shapes of
    ``compute_grads`` (``jax.eval_shape`` — nothing executes, the lint
    is fully static: trace + AOT compile only).

    The sharding/reshard/memory passes run by default against the
    example's OWN declared plan (``build_training`` returns its
    regex→PartitionSpec rule table and the DDP engine's collective
    plan): params/scaler must stay replicated, the batch must shard
    over dp, the step body may contain only the declared gradient
    sync, and ``--budget`` arms the static peak-HBM gate.  On a
    single-device run the sharding pass has nothing to prove and
    stays quiet — run under
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (as the
    ``verify_tier1.sh`` gate does) to prove the real mesh.
    """
    import jax

    from apex_tpu import analysis

    mod = _load_resilient_module()
    t = mod.build_training(accum=args.accum, wire=args.wire)
    state, batch = t["state"], t["batch_fn"](0)
    expect_sharding = t.get("expect_sharding")
    expect_plan = t.get("expect_plan")

    grads_args = (state["params"], state["scaler"], batch)
    report = analysis.check(
        t["compute_grads"], *grads_args,
        expect_collectives=args.expect,
        expect_sharding=expect_sharding,
        expect_plan=expect_plan,
        hbm_budget=args.budget,
        name="resilient/compute_grads",
    )

    loss_shape, scaled_shape = jax.eval_shape(
        t["compute_grads"], *grads_args
    )
    # the optimizer update runs replicated (no shard_map): its plan is
    # "no collectives at all" — anything above the latency tolerance
    # is an unplanned reshard
    up = analysis.check(
        t["apply_update"], scaled_shape, state, loss_shape,
        expect_plan=(
            {"mesh": expect_plan["mesh"], "collectives": []}
            if expect_plan else None
        ),
        hbm_budget=args.budget,
        name="resilient/apply_update",
    )
    analysis.attach_shard_sections(report, [
        ("resilient/compute_grads", report.hlo_text),
        ("resilient/apply_update", up.hlo_text),
    ], expect_sharding=expect_sharding)
    report.merge(up)
    report.target = "resilient"
    return report


def _load_serve_module():
    import importlib.util

    path = os.path.join(
        REPO, "examples", "simple", "serve", "serve_gpt.py"
    )
    spec = importlib.util.spec_from_file_location("serve_gpt", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def lint_serve(args):
    """Check the serve example's AOT prefill + decode step programs.

    ``build_serving`` is the example's own engine constructor, so the
    compiled programs under lint are the ones the example dispatches;
    ``engine.lint()`` runs ``analysis.check`` (with the cache donation
    declared) over the smallest prefill bucket and the decode step
    without the build-time raise, so findings render instead of
    aborting."""
    import jax

    from apex_tpu.models.gpt import GptModel

    mod = _load_serve_module()
    cfg = mod.model_config()
    params = GptModel(cfg).init(
        jax.random.PRNGKey(0), jax.numpy.zeros((8, 1), jax.numpy.int32)
    )
    kv_wire = "int8" if args.wire == "int8" else "f32"
    engine = mod.build_serving(
        params, kv_wire=kv_wire, verify=False,
        hbm_budget_bytes=args.budget,
    )
    return engine.lint()


def lint_train(args):
    """Check the composable trainer's fused dp×tp step.

    The trainer verifies ITSELF at build (``TrainConfig(verify=
    "error")`` raises on any ERROR finding — the ISSUE 12 contract);
    here it builds with ``verify="off"`` and the report is produced
    explicitly so findings RENDER (with the shard-plan/memory sections
    attached) instead of aborting the tool."""
    from apex_tpu.train import build_demo

    step = build_demo(
        args.dp, args.tp, wire=args.wire, verify="off",
        hbm_budget=args.budget,
    )
    report = step.verify()
    report.target = f"train/dp{args.dp}tp{args.tp}/{step.mode}"
    return report


def lint_hlo_file(args):
    from apex_tpu import analysis

    with open(args.hlo) as f:
        text = f.read()
    report = analysis.lint_hlo(
        text,
        donated=args.donated,
        expect_collectives=args.expect,
        hbm_budget=args.budget,
        name=os.path.basename(args.hlo),
    )
    analysis.attach_shard_sections(
        report, [(report.target, text)]
    )
    return report


def main():
    ap = argparse.ArgumentParser(
        description="static graph lint over step programs "
        "(rule catalog: docs/analysis.md)"
    )
    ap.add_argument("--target", choices=["resilient", "serve", "train"],
                    default=None)
    ap.add_argument("--hlo", metavar="FILE", default=None,
                    help="lint an optimized-HLO text dump instead of "
                    "building a target")
    ap.add_argument("--wire", default="f32",
                    choices=["f32", "bf16", "int8"])
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--dp", type=int, default=2,
                    help="train-target dp axis size (default 2)")
    ap.add_argument("--tp", type=int, default=2,
                    help="train-target tp axis size (default 2)")
    ap.add_argument("--expect", type=json.loads, default=None,
                    metavar="JSON", help="collective expectations")
    ap.add_argument("--donated", type=int, default=None,
                    help="declared donated-leaf count (--hlo mode)")
    ap.add_argument("--budget", type=int, default=None, metavar="BYTES",
                    help="static peak-HBM budget in bytes — exceeding "
                    "it is a memory-budget ERROR (docs/analysis.md)")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the report as one JSON object")
    ap.add_argument("--fail-on", choices=["error", "warning"],
                    default="error")
    args = ap.parse_args()

    if bool(args.target) == bool(args.hlo):
        ap.error("exactly one of --target / --hlo is required")

    if args.hlo:
        report = lint_hlo_file(args)
    elif args.target == "serve":
        report = lint_serve(args)
    elif args.target == "train":
        report = lint_train(args)
    else:
        report = lint_resilient(args)

    # ride the observability board like every other subsystem, so a
    # host process embedding this as a library sees the same gauges
    from apex_tpu import analysis

    analysis.publish_report(report)

    print(report.render())
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report.to_json(), f, indent=2)
            f.write("\n")
        print(f"[graph_lint] wrote {args.json}", file=sys.stderr)
    return 0 if report.ok(fail_on=args.fail_on) else 1


if __name__ == "__main__":
    sys.exit(main())
