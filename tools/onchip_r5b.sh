#!/bin/sh
# Round-5b staged queue: everything the mid-round tunnel drop cut short,
# in value order.  Assumes the r5a queue (tools/onchip_queue.sh 5)
# already ran: tests_tpu 54/54, grid2 A/B, tile sweeps, BENCH_all_r05.
#
#   sh tools/onchip_r5b.sh
#
#   1. bench_all --round 5  — refresh: flash_attention._TUNED_TILES is
#      now populated from the r5a sweeps, so the long_attn line should
#      move ~43 -> ~60 TFLOP/s and the mha line may improve too.
#   2. trace capture + summary on the headline — the docs/mfu.md
#      lever-#2 (copies) attribution input.
#   3. attn_tune --bwd-only --shapes mha — the (512|1024, *) bwd cells
#      the tunnel drop left unmeasured.
# Logs land in onchip_r5b.*.log at the repo root.

set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO" || exit 1

step() {
    name="$1"; shift
    log="onchip_r5b.$name.log"
    if ! sh tools/tpu_probe.sh 120; then
        echo "[$name] SKIPPED: probe failed (tunnel down)" | tee -a "$log"
        return 1
    fi
    echo "[$name] start $(date -u +%H:%M:%S)" | tee -a "$log"
    timeout 2700 "$@" >>"$log" 2>&1
    rc=$?
    echo "[$name] done rc=$rc $(date -u +%H:%M:%S)" | tee -a "$log"
    return $rc
}

# Preserve the complete r5a artifact before the refresh: bench_all
# writes BENCH_all_r05.json even on partial failure, and a mid-bench
# tunnel drop must not clobber the round's only complete line set.
[ -f BENCH_all_r05.json ] && [ ! -f BENCH_all_r05a.json ] \
    && cp BENCH_all_r05.json BENCH_all_r05a.json
step bench_all python tools/bench_all.py --round 5
step trace python bench.py --config bert_lamb --trace trace_r05 \
    --hlo-out hlo_r05.txt
step trace_summary python tools/trace_summary.py trace_r05 -n 40 \
    --hlo hlo_r05.txt
step attn_tune_mha python tools/attn_tune.py --bwd-only --shapes mha
#   4. probe past the 1024 tile cap at the long shape: r5a's optimum sat
#      at the edge of the swept grid on every kernel.
step attn_big_fwd python tools/attn_tune.py --fwd-only --shapes long \
    --blocks 1024,2048
step attn_big_bwd python tools/attn_tune.py --bwd-only --shapes long \
    --blocks 1024,2048
#   5. one combined fwd+bwd cell at the winner tiles: validates the
#      value-pull sync fix on chip (the pre-fix combined mode
#      under-waited; post-fix it should land near fwd + bwd-only sums)
step attn_combined python tools/attn_tune.py --shapes long --blocks 1024
echo "r5b queue finished $(date -u)"
