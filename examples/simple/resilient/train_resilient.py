"""Minimal resilient training loop — survives NaN bursts and preemption.

A tiny linear-regression job wrapped in the full resilience stack:
guarded amp steps (NaN/spike skip), step-numbered checkpoints with retry,
SIGTERM-safe shutdown, and auto-resume.  Run it, kill it (``kill -TERM``
or let chaos do it), run it again — it continues where it stopped::

    python train_resilient.py --steps 200 --dir /tmp/resilient_demo

    # with injected faults (deterministic; the x1 save fault heals on retry):
    APEX_TPU_CHAOS="grads:nan@7,8;checkpoint_save:raise:x1@5;preemption@42" \
        python train_resilient.py --steps 200 --dir /tmp/resilient_demo
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam
from apex_tpu.resilience import GradGuard, chaos, guarded_amp_update, run_resilient


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--dir", default="/tmp/apex_tpu_resilient_demo")
    ap.add_argument("--save-every", type=int, default=10)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x_all = jnp.asarray(rs.randn(4096, 8), jnp.float32)
    w_true = jnp.asarray(rs.randn(8, 4), jnp.float32)
    y_all = x_all @ w_true

    params = {"w": jnp.zeros((8, 4), jnp.float32)}
    tx = fused_adam(1e-2)
    scaler = amp.DynamicLossScaler(init_scale=2.0**10)
    guard = GradGuard(spike_factor=20.0, warmup_steps=5)

    state = {
        "params": params,
        "opt": tx.init(params),
        "scaler": scaler.init(),
        "guard": guard.init(),
    }

    @jax.jit
    def compute_grads(params, scaler_state, batch):
        x, y = batch

        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        scaled = jax.tree_util.tree_map(
            lambda g: scaler.scale(g, scaler_state), grads
        )
        return loss, scaled

    def batch_fn(step):
        lo = (step * 64) % (4096 - 64)
        return x_all[lo : lo + 64], y_all[lo : lo + 64]

    def step_fn(state, batch):
        loss, scaled = compute_grads(state["params"], state["scaler"], batch)
        # chaos GRADS site: poisons the tree on scheduled steps, no-op else
        scaled = chaos.corrupt_tree(scaled, int(state["guard"].step))
        p, o, s, g, verdict = guarded_amp_update(
            tx, scaler, guard, scaled, state["opt"], state["params"],
            state["scaler"], state["guard"],
        )
        new_state = {"params": p, "opt": o, "scaler": s, "guard": g}
        if bool(verdict.skipped):
            print(f"  step skipped (found_inf={float(verdict.found_inf)}, "
                  f"spike={bool(verdict.spike)})")
        return new_state, {"skipped": verdict.skipped, "loss": loss}

    result = run_resilient(
        step_fn,
        state,
        batch_fn,
        directory=args.dir,
        num_steps=args.steps,
        save_interval_steps=args.save_every,
        max_to_keep=3,
        rollback_after=5,
    )
    print(
        f"done: last_step={result.last_step} resumed_from={result.resumed_from} "
        f"steps_run={result.steps_run} skipped={result.skipped_steps} "
        f"rollbacks={result.rollbacks} preempted={result.preempted}"
    )
    final_loss = float(
        jnp.mean((x_all @ result.state["params"]["w"] - y_all) ** 2)
    )
    print(f"final loss: {final_loss:.6f}")


if __name__ == "__main__":
    main()
