"""Minimal resilient training loop — survives NaN bursts and preemption.

A tiny linear-regression job wrapped in the full resilience stack:
guarded amp steps (NaN/spike skip), step-numbered checkpoints with retry,
SIGTERM-safe shutdown, and auto-resume.  Run it, kill it (``kill -TERM``
or let chaos do it), run it again — it continues where it stopped::

    python train_resilient.py --steps 200 --dir /tmp/resilient_demo

    # with injected faults (deterministic; the x1 save fault heals on retry):
    APEX_TPU_CHAOS="grads:nan@7,8;checkpoint_save:raise:x1@5;preemption@42" \
        python train_resilient.py --steps 200 --dir /tmp/resilient_demo

Gradient accumulation rides the DDP comm layer (``docs/comm.md``):
``--accum K`` splits each optimizer step into K microbatches whose grads
accumulate LOCALLY (``DistributedDataParallel.no_sync`` semantics —
Apex's ``delay_allreduce``), paying ONE gradient sync on the boundary;
``--wire int8`` makes that boundary sync quantized.  The loss runs
through a ``shard_map`` over the dp mesh, so the same script spans
1..N devices (``XLA_FLAGS=--xla_force_host_platform_device_count=4`` to
try a 4-way mesh on CPU)::

    python train_resilient.py --steps 100 --accum 4 --wire int8

``--metrics-out out.jsonl`` turns on the full observability pipe
(``docs/observability.md``): device metrics (loss, grad norm, scaler
scale, skip counts) accumulate INSIDE the jitted update and are fetched
on a cadence, a ``StepMeter`` adds wall-clock step time / tokens/s /
MFU, a ``GoodputAccountant`` rides the ``run_resilient`` observer
events, and everything lands in the bench-schema JSONL.  The final
``train/goodput`` line carries the exact skip/rollback/retry counts of
the run (``GoodputAccountant.snapshot()``), so a chaos drill is
checkable from the artifact alone.  ``APEX_TPU_TRACE_STEPS="N+K"`` arms
a profile window of steps N..N+K-1 with no further flags.

Crash forensics and health monitoring are ON BY DEFAULT:

- a ``FlightRecorder`` (``--flight N[:DIR]``, default ring of 64 into
  ``<--dir>/flight/``; ``--flight 0`` disables) keeps the last steps'
  guard/scaler/loss state and dumps ``flight_<ts>.json`` atomically
  when the run dies — skip-budget exhaustion, an unhandled exception,
  SIGTERM.  Render it with ``tools/flight_view.py``.
- a health ``Watchdog`` (``--no-health`` disables) evaluates the
  default rule set (goodput/MFU floors, loss spikes, NaN-storm rate,
  stale fetches, hung steps — plus per-host stragglers when a
  multi-device mesh feeds the fleet aggregator) and prints each
  ``HealthEvent``, mirrors it into the flight recorder and — with
  ``--metrics-out`` — the JSONL.
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../../.."))
)

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu import observability as obs
from apex_tpu.optimizers import fused_adam
from apex_tpu.resilience import (
    GradGuard,
    ObserverFanout,
    chaos,
    run_resilient,
)
from apex_tpu.train import TrainConfig, Trainer


def build_training(accum=1, wire="f32", fetch_every=8):
    """Construct the example's full training program — mesh, toy data,
    guarded/metered state, and the two jitted step functions — on top
    of the composable trainer (``apex_tpu.train``, docs/training.md):
    the example proves the COMPOSED path end to end, not a bespoke one.
    ``Trainer.build_guarded`` owns the mesh, the DDP comm engine
    (``wire``/accum boundary sync), the guarded-amp update, the
    in-step metric fold, and the declared sharding/collective plans.

    Shared by :func:`main` and ``tools/graph_lint.py --target
    resilient``: the CI lint gate audits EXACTLY the compiled programs
    this example dispatches, not a lookalike.  Returns a dict with the
    jitted ``compute_grads(params, scaler_state, batch)`` and
    ``apply_update(scaled, state, loss)``, plus the pieces main() (or a
    linter) needs to drive or trace them: ``state``, ``batch_fn``,
    ``registry``, ``mesh``/``dp``/``rows``, and the raw
    ``tx``/``scaler``/``guard``/``ddp``/``x_all``/``y_all``.
    """
    dp = len(jax.devices())  # all devices -> the trainer's dp axis
    micro = 64  # rows per microbatch, per replica
    rows = micro * dp * accum  # rows consumed per optimizer step
    if rows > 4096:  # the toy dataset below
        raise SystemExit(
            f"--accum {accum} x dp={dp} needs {rows} rows per step "
            "but the toy dataset has 4096; lower --accum or the mesh size"
        )

    rs = np.random.RandomState(0)
    x_all = jnp.asarray(rs.randn(4096, 8), jnp.float32)
    w_true = jnp.asarray(rs.randn(8, 4), jnp.float32)
    y_all = x_all @ w_true

    params = {"w": jnp.zeros((8, 4), jnp.float32)}
    tx = fused_adam(1e-2)
    scaler = amp.DynamicLossScaler(init_scale=2.0**10)
    guard = GradGuard(spike_factor=20.0, warmup_steps=5)

    # -- observability ------------------------------------------------------
    # The registry (and its slot in the checkpointed state) exists
    # UNCONDITIONALLY so the checkpoint tree structure never depends on
    # the --metrics-out flag: a run interrupted without telemetry can
    # resume with it (and vice versa) on the same --dir.  Only the
    # reporting side — meter, goodput ledger, sinks — is gated.
    registry = obs.MetricRegistry(fetch_every=fetch_every)
    registry.gauge("train/loss", unit="mse")
    registry.counter("guard/skipped")
    for name in ("guard/found_inf", "guard/spike", "guard/grad_norm",
                 "guard/norm_ema", "guard/consecutive_skips",
                 "guard/total_skips", "guard/budget_left",
                 "amp/loss_scale", "amp/growth_tracker",
                 "amp/hysteresis"):
        registry.gauge(name)

    # -- the composed trainer ----------------------------------------------
    # A 1D dp mesh, replicated params (the DDP contract), the comm
    # engine's wire format on the accumulation-boundary sync.  The
    # guarded two-phase shape keeps the gradient tree on the host
    # between the two programs — the chaos `grads` site needs it there.
    trainer = Trainer(TrainConfig(
        mesh={"dp": dp},
        rules=[(r".*", jax.sharding.PartitionSpec())],
        wire=wire,
        update_sharding="replicate",  # the guard wants the full tree
    ))
    g = trainer.build_guarded(
        lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
        params,
        tx=tx, scaler=scaler, guard=guard,
        registry=registry, accum=accum,
    )

    def batch_fn(step):
        span = x_all.shape[0] - rows  # 0 when one step eats the dataset
        lo = (step * rows) % span if span > 0 else 0
        shape = (accum, micro * dp)
        return (
            x_all[lo: lo + rows].reshape(*shape, 8),
            y_all[lo: lo + rows].reshape(*shape, 4),
        )

    return {
        "mesh": g.mesh, "dp": dp, "micro": micro, "rows": rows,
        "x_all": x_all, "y_all": y_all,
        "state": g.state, "registry": registry,
        "tx": tx, "scaler": scaler, "guard": guard, "ddp": g.ddp,
        "trainer": trainer,
        "compute_grads": g.compute_grads, "apply_update": g.apply_update,
        "batch_fn": batch_fn,
        "shard_rules": g.shard_rules,
        "expect_sharding": g.expect_sharding,
        "expect_plan": g.expect_plan,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--dir", default="/tmp/apex_tpu_resilient_demo")
    ap.add_argument("--save-every", type=int, default=10)
    ap.add_argument("--accum", type=int, default=1,
                    help="microbatches accumulated locally per optimizer "
                    "step (one gradient sync on the boundary)")
    ap.add_argument("--wire", default="f32",
                    choices=["f32", "bf16", "int8"],
                    help="wire format of the boundary gradient sync "
                    "(docs/comm.md; tiny leaves stay on the exact psum)")
    ap.add_argument("--ckpt-engine", default="async",
                    choices=["async", "sync"],
                    help="checkpoint save engine (docs/goodput.md): "
                    "async = zero-stall host snapshot + background "
                    "write (default); sync = orbax manager inline")
    ap.add_argument("--metrics-out", default=None,
                    help="JSONL telemetry path — turns on the full "
                    "observability pipe (docs/observability.md)")
    ap.add_argument("--fetch-every", type=int, default=8,
                    help="device->host metric fetch cadence in steps")
    ap.add_argument("--report-every", type=int, default=10,
                    help="steps between JSONL telemetry reports")
    ap.add_argument("--flight", default=None, metavar="N[:DIR]",
                    help="flight-recorder ring size (+ optional dump "
                    "dir; default 64 into <--dir>/flight; 0 disables; "
                    "APEX_TPU_FLIGHT overrides)")
    ap.add_argument("--no-health", action="store_true",
                    help="disable the health watchdog (on by default: "
                    "goodput/MFU floors, loss spike, NaN rate, stale "
                    "fetch, hung step, straggler)")
    ap.add_argument("--ops-port", type=int, default=None, metavar="PORT",
                    help="serve live OpenMetrics at /metrics while the "
                    "run trains (0 = OS-assigned; APEX_TPU_OPS_PORT is "
                    "the default; docs/observability.md 'Live ops plane')")
    args = ap.parse_args()
    if args.ops_port is None:
        from apex_tpu.observability.ometrics import ops_port_from_env

        args.ops_port = ops_port_from_env()

    t = build_training(
        accum=args.accum, wire=args.wire, fetch_every=args.fetch_every
    )
    dp, rows = t["dp"], t["rows"]
    x_all, y_all = t["x_all"], t["y_all"]
    state, registry = t["state"], t["registry"]
    compute_grads, apply_update = t["compute_grads"], t["apply_update"]
    batch_fn = t["batch_fn"]
    print(f"devices: dp={dp}, accum={args.accum}, wire={args.wire}")

    # meter + goodput ledger run unconditionally (cheap, host-side) so
    # the flight recorder and watchdog see them with or without a JSONL
    # reporter; only the report fan-out is gated on --metrics-out
    n_params = sum(
        p.size for p in jax.tree_util.tree_leaves(state["params"])
    )
    meter = obs.StepMeter(
        tokens_per_step=rows,
        flops_per_step=obs.transformer_train_flops(n_params, rows),
    )
    goodput = obs.GoodputAccountant()
    reporter = None
    if args.metrics_out:
        reporter = obs.Reporter(
            [obs.JSONLSink(args.metrics_out)],
            registry=registry, meter=meter, goodput=goodput,
        )
    tracer = obs.TraceScheduler()  # armed by APEX_TPU_TRACE_STEPS, else no-op

    # live ops plane: scrape the registry + board while the run trains
    # (the memstats collect hook publishes HBM watermarks per scrape —
    # real memory_stats() on TPU, silently absent on the CPU backend)
    ops = None
    if args.ops_port is not None:
        mem_provider = obs.memstats.default_provider()
        monitor = (
            obs.MemStatsMonitor(mem_provider)
            if mem_provider is not None else None
        )
        ops = obs.OpsServer(
            registries=[registry],
            collect=monitor.sample if monitor is not None else None,
            port=args.ops_port,
        ).start()
        print(f"ops: live OpenMetrics at {ops.url}")

    # flight recorder: env > --flight > default ring of 64.  Resolved
    # to ONE spec before from_env so APEX_TPU_FLIGHT=0 genuinely
    # disables (an `or`-chain over recorders would fall through a
    # disabled env spec into the default and arm anyway).
    from apex_tpu.observability.flight import ENV_FLIGHT

    spec = os.environ.get(ENV_FLIGHT) or args.flight or "64"
    flight = obs.FlightRecorder.from_env(
        spec,
        directory=os.path.join(args.dir, "flight"),
        run={"example": "train_resilient", "steps": args.steps,
             "accum": args.accum, "wire": args.wire, "dp": dp},
    )
    if flight is not None:
        flight.attach(registry=registry, meter=meter, goodput=goodput)

    # fleet aggregation feeds the straggler rule on a multi-device mesh
    # (one jitted all-gather on the fetch cadence, docs/observability.md)
    fleet = None
    if dp > 1:
        fleet = obs.FleetAggregator(
            ("train/step_time_ms", "train/mfu", "train/loss"),
            mesh=t["mesh"], every=args.fetch_every,
        )

    watchdog = None
    if not args.no_health:
        watchdog = obs.Watchdog(
            registry=registry, meter=meter, goodput=goodput, fleet=fleet,
            reporter=reporter, flight=flight,
            on_unhealthy=lambda ev: print(
                f"  [health/{ev.severity}] {ev.rule}: {ev.message}"
            ),
            check_every=max(1, args.fetch_every // 2),
        )

    def step_fn(state, batch):
        step = int(state["guard"].step)
        tracer.on_step(step)
        loss, scaled = compute_grads(state["params"], state["scaler"], batch)
        # chaos GRADS site: poisons the tree on scheduled steps, no-op else
        scaled = chaos.corrupt_tree(scaled, step)
        new_state, verdict = apply_update(scaled, state, loss)
        registry.observe(step, new_state["metrics"])
        meter.tick()
        if fleet is not None:
            fleet.observe(step, {**registry.values(), **meter.summary()})
        if reporter is not None and step % args.report_every == 0:
            reporter.report(step)
        if bool(verdict.skipped):
            print(f"  step skipped (found_inf={float(verdict.found_inf)}, "
                  f"spike={bool(verdict.spike)})")
        return new_state, {"skipped": verdict.skipped, "loss": loss}

    result = None
    try:
        result = run_resilient(
            step_fn,
            state,
            batch_fn,
            directory=args.dir,
            num_steps=args.steps,
            save_interval_steps=args.save_every,
            max_to_keep=3,
            rollback_after=5,
            observer=ObserverFanout([goodput, watchdog]),
            flight=flight,
            checkpoint=args.ckpt_engine,
        )
    finally:
        # even a raising run (e.g. max_rollbacks exhausted) must close
        # an armed trace window and land its final telemetry — those
        # are exactly the artifacts needed to debug the failure
        tracer.stop()
        # a captured window gets attributed on the way out: the
        # compute/collective/host-stall split lands on the board (the
        # watchdog fraction rules' source) and — with --metrics-out —
        # in the JSONL (docs/observability.md "Attribution & roofline")
        if tracer.log_dir and os.path.isdir(tracer.log_dir):
            try:
                from apex_tpu.observability import attribution as attr

                meas = attr.attribute_trace_dir(tracer.log_dir)
                fr = attr.publish_attribution(meas, reporter=reporter)
                print(
                    "trace attribution (steps %s..%s): compute=%.3f "
                    "collective=%.3f host_stall=%.3f "
                    "(tools/step_profile.py adds the roofline)"
                    % (tracer.start, tracer.end, fr["compute"],
                       fr["collective"], fr["host_stall"])
                )
            except Exception as e:  # the postmortem must not eat the run
                print(f"trace attribution failed: {e}", file=sys.stderr)
        if ops is not None:
            ops.stop()
        if reporter is not None:
            registry.fetch()  # drain the async buffers for the report
            final_step = (
                max(result.last_step, 0) if result is not None
                else meter.steps
            )
            reporter.report(final_step)
            # The consolidated goodput line: value + the EXACT event
            # counts of this invocation (they match RunResult by
            # construction — the accountant saw every on_step /
            # on_rollback the runner counted).
            snap = goodput.snapshot()
            reporter.sinks[0].write(obs.bench_record(
                "train/goodput", snap["goodput"],
                "fraction (productive/executed)", None,
                step=final_step, accepted=snap["accepted"],
                skipped=snap["skipped"], discarded=snap["discarded"],
                rollbacks=snap["rollbacks"], retries=snap["retries"],
                resumes=snap["resumes"], preempted=snap["preempted"],
            ))
            reporter.close()
    print(
        f"done: last_step={result.last_step} resumed_from={result.resumed_from} "
        f"steps_run={result.steps_run} skipped={result.skipped_steps} "
        f"rollbacks={result.rollbacks} preempted={result.preempted}"
    )
    saves = obs.board.get("goodput/ckpt/saves")
    if saves:
        # the async engine's ledger (docs/goodput.md): the only step-path
        # cost is the snapshot — stall_frac is the <1% acceptance number
        print(
            "ckpt: engine=%s saves=%d writes=%d stall_frac=%.5f "
            "last_write=%.1fms"
            % (args.ckpt_engine, saves,
               obs.board.get("goodput/ckpt/writes", 0),
               obs.board.get("goodput/ckpt/stall_frac", 0.0),
               obs.board.get("goodput/ckpt/last_write_ms", 0.0))
        )
    final_loss = float(
        jnp.mean((x_all @ result.state["params"]["w"] - y_all) ** 2)
    )
    print(f"final loss: {final_loss:.6f}")


if __name__ == "__main__":
    main()
