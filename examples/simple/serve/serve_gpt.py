"""Train → checkpoint → serve: the handoff round-trip, end to end.

Phase 1 trains a tiny GPT with the resilient runner (the same
``run_resilient`` + step-numbered checkpoints the training example
uses), phase 2 **restores the checkpoint from disk** and serves it
through the full serving stack — AOT inference engine, paged KV cache,
continuous-batching scheduler — proving the train→serve handoff
round-trips through ``checkpoint/``::

    python serve_gpt.py --dir /tmp/serve_demo --metrics-out serve.jsonl

The round-trip is asserted, not assumed: the restored parameter tree
must match the in-memory training result bit-for-bit before serving
starts.  Serving telemetry (TTFT, tokens/s, queue depth, batch fill,
page occupancy) rides the observability spine into the ``--metrics-out``
JSONL — the same bench-line schema training telemetry uses — and a
serving :class:`~apex_tpu.observability.health.Watchdog`
(``serve_rules``: TTFT deadline, queue depth, stale fetch, hung step)
prints any health event.  The engine's build runs
``apex_tpu.analysis.check`` over every compiled step (transfer-free,
donation-aliased); the zero-ERROR verdict is printed as the lint proof
(``tools/graph_lint.py --target serve`` re-checks it in CI).

``--kv-wire int8`` serves from a blockwise-int8 KV cache and
``--weight-wire int8`` packs the weights on the same codec
(``docs/comm.md``).
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../../.."))
)

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import observability as obs
from apex_tpu.models.gpt import GptConfig, GptModel, gpt_lm_loss
from apex_tpu.optimizers import fused_adam
from apex_tpu.resilience import run_resilient
from apex_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
    ServeConfig,
)


def model_config():
    # tiny on purpose: the example is about the PIPELINE, and it must
    # finish in seconds on CPU (verify_tier1.sh SERVE pass)
    return GptConfig(
        vocab_size=96, hidden_size=48, num_layers=2, num_heads=4,
        intermediate_size=96, max_seq_len=256, dtype=jnp.float32,
    )


def build_serving(params, *, kv_wire="f32", weight_wire="f32",
                  registry=None, verify=True, hbm_budget_bytes=None):
    """Engine for the example's model — importable so
    ``tools/graph_lint.py --target serve`` lints EXACTLY the compiled
    programs this example dispatches (it passes ``verify=False`` and
    renders ``engine.lint()`` instead of tripping the build raise).
    ``hbm_budget_bytes`` arms the build-time static peak-HBM gate
    (docs/analysis.md "Sharding & memory passes")."""
    cfg = model_config()
    engine = InferenceEngine(
        cfg, params,
        ServeConfig(
            page_size=8, num_pages=64, max_batch=4, max_pages_per_seq=8,
            kv_wire=kv_wire, weight_wire=weight_wire, verify=verify,
            hbm_budget_bytes=hbm_budget_bytes,
        ),
        registry=registry,
    )
    return engine


def train(args):
    """Phase 1: resilient training with step-numbered checkpoints."""
    cfg = model_config()
    model = GptModel(cfg)
    seq, batch = 32, 4
    rs = np.random.RandomState(0)
    data = jnp.asarray(
        rs.randint(0, cfg.vocab_size, size=(4096,)), jnp.int32
    )

    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((seq, batch), jnp.int32)
    )
    tx = fused_adam(1e-3)
    state = {"params": params, "opt": tx.init(params)}

    @jax.jit
    def train_step(state, batch_ids):
        loss, grads = jax.value_and_grad(gpt_lm_loss)(
            state["params"], model, batch_ids
        )
        updates, opt = tx.update(grads, state["opt"], state["params"])
        import optax

        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt}, loss

    def batch_fn(step):
        lo = (step * seq * batch) % (data.shape[0] - seq * batch)
        return data[lo: lo + seq * batch].reshape(seq, batch)

    losses = []

    def step_fn(state, batch_ids):
        state, loss = train_step(state, batch_ids)
        losses.append(float(loss))
        return state, {"loss": loss}

    result = run_resilient(
        step_fn, state, batch_fn,
        directory=os.path.join(args.dir, "checkpoint"),
        num_steps=args.train_steps,
        save_interval_steps=args.save_every,
        max_to_keep=2,
    )
    print(
        f"trained {result.steps_run} steps "
        f"(resumed_from={result.resumed_from}); "
        f"loss {losses[0]:.4f} -> {losses[-1]:.4f}"
        if losses else
        f"training resumed complete at step {result.last_step}"
    )
    return result.state


def restore(args, template):
    """Phase 2 entry: the params come from DISK, not from memory."""
    from apex_tpu import checkpoint

    ckpt_dir = os.path.join(args.dir, "checkpoint")
    step = checkpoint.latest_step(ckpt_dir)
    if step is None:
        raise SystemExit(f"no checkpoint under {ckpt_dir} — train first")
    with checkpoint.CheckpointManager(ckpt_dir) as mgr:
        restored = mgr.restore(step, template=template)
    print(f"restored checkpoint step {step} from {ckpt_dir}")
    return restored["params"]


def serve(args, params):
    registry = obs.MetricRegistry(fetch_every=1)
    engine = build_serving(
        params, kv_wire=args.kv_wire, weight_wire=args.weight_wire,
        registry=registry,
    ).build()
    errors = {n: len(r.errors()) for n, r in engine.reports.items()}
    print(f"engine built: graph lint ERRORs per step = {errors} "
          f"(compiled {sorted(engine.compile_counts)})")

    reporter = None
    if args.metrics_out:
        reporter = obs.Reporter(
            [obs.JSONLSink(args.metrics_out)], registry=registry
        )
    watchdog = obs.Watchdog(
        obs.serve_rules(ttft={"deadline_ms": args.slo_ttft_ms}),
        registry=registry, reporter=reporter, check_every=1,
        on_unhealthy=lambda ev: print(
            f"  [health/{ev.severity}] {ev.rule}: {ev.message}"
        ),
    )

    sched = ContinuousBatchingScheduler(engine, registry=registry)
    rs = np.random.RandomState(1)
    for i in range(args.requests):
        sched.submit(Request(
            prompt=[int(t) for t in
                    rs.randint(0, 96, size=int(rs.choice([8, 12, 20])))],
            max_new_tokens=int(rs.choice([4, 8])),
            slo_ttft_ms=args.slo_ttft_ms,
        ))
    step = 0
    while sched.pending:
        sched.step()
        step += 1
        watchdog.on_step(step)
        if reporter is not None:
            reporter.report(step)
    registry.fetch()
    vals = registry.values()
    if reporter is not None:
        reporter.report(step)
        reporter.close()
    print(
        "served %d requests (%d shed): ttft=%.2fms tokens/s=%.1f "
        "batch_fill=%.2f retraces=%d"
        % (len(sched.completed), len(sched.shed),
           vals.get("serve/ttft_ms", float("nan")),
           vals.get("serve/tokens_per_s", 0.0),
           vals.get("serve/batch_fill", 0.0), engine.retraces)
    )
    for r in sched.completed[:3]:
        print(f"  request {r.rid}: prompt[:6]={r.prompt[:6]} -> "
              f"tokens={r.tokens}")
    return sched


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="/tmp/apex_tpu_serve_demo")
    ap.add_argument("--train-steps", type=int, default=12)
    # every step, so the LAST step is always on disk and the
    # restored-equals-trained proof below is exact (a sparser cadence
    # restores the last saved step instead — fine for serving, but the
    # example is the round-trip demonstration)
    ap.add_argument("--save-every", type=int, default=1)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--slo-ttft-ms", type=float, default=5000.0)
    ap.add_argument("--kv-wire", default="f32", choices=["f32", "int8"])
    ap.add_argument("--weight-wire", default="f32",
                    choices=["f32", "int8"])
    ap.add_argument("--metrics-out", default=None,
                    help="serving telemetry JSONL (bench-line schema)")
    args = ap.parse_args()

    final_state = train(args)
    params = restore(args, template=final_state)

    # the round-trip PROOF: what came off disk is what training ended
    # with, leaf for leaf
    mismatches = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))),
        params, final_state["params"],
    ))
    assert max(mismatches) == 0.0, (
        f"restored params drifted from the training result: "
        f"max|delta|={max(mismatches)}"
    )
    print("train->serve handoff round-trips: restored == trained "
          f"({len(mismatches)} leaves, bit-exact)")

    serve(args, params)


if __name__ == "__main__":
    main()
