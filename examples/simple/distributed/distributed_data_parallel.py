"""Minimal data-parallel script — ≙
``examples/simple/distributed/distributed_data_parallel.py``.

The reference launches one process per GPU (``torch.distributed.launch``),
wraps the model in apex DDP and all-reduces grads.  SPMD inverts the
shape: ONE process, a mesh over all devices, and the DDP wrapper builds
the jitted step.  Run directly (any device count):

    python examples/simple/distributed/distributed_data_parallel.py
"""

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../../.."))
)

import jax
import jax.numpy as jnp
import optax

from apex_tpu import parallel_state as ps
from apex_tpu.parallel import DistributedDataParallel

D = 16


def loss_fn(params, batch):
    x, y = batch
    pred = jnp.tanh(x @ params["w"]) @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def main():
    mesh = ps.initialize_model_parallel()  # all devices -> dp axis
    dp = ps.get_data_parallel_world_size()
    print(f"devices: {dp} ({jax.devices()[0].device_kind})")

    key = jax.random.PRNGKey(0)
    params = {
        "w": jax.random.normal(key, (D, D)) * 0.3,
        "w2": jax.random.normal(jax.random.fold_in(key, 1), (D, D)) * 0.3,
    }
    x = jax.random.normal(jax.random.PRNGKey(1), (8 * dp, D))
    y = jax.random.normal(jax.random.PRNGKey(2), (8 * dp, D))

    ddp = DistributedDataParallel(lambda p, b: loss_fn(p, b))
    step = ddp.make_step(optax.sgd(0.1), mesh)
    opt_state = optax.sgd(0.1).init(params)

    for i in range(20):
        params, opt_state, loss = step(params, opt_state, (x, y))
        if i % 5 == 0:
            print(f"step {i:2d}  loss {float(loss):.5f}")
    print("final loss:", float(loss))


if __name__ == "__main__":
    main()
