"""GPT causal-LM training — decoder stack over the full parallelism menu.

Demonstrates the pieces BASELINE #5 benches plus the beyond-reference
axes: tensor parallelism (+ Megatron SP), context parallelism (ring or
Ulysses attention for long sequences), and Switch-MoE expert
parallelism, over the packed-corpus input pipeline.

    python examples/gpt/train_gpt.py --steps 16 --batch 8 --seq-len 512
    python examples/gpt/train_gpt.py --context-parallel ring --seq-len 2048
    python examples/gpt/train_gpt.py --tp 4 --sequence-parallel
    python examples/gpt/train_gpt.py --num-experts 8
    # tiny CPU smoke:
    APEX_TPU_FORCE_CPU=1 python examples/gpt/train_gpt.py --tiny
"""

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../.."))
)

import argparse
import tempfile
import time

if os.environ.get("APEX_TPU_FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.data import (
    DataLoader,
    TokenFileDataset,
    synthetic_token_corpus,
)
from apex_tpu.models.gpt import (
    GptConfig,
    GptModel,
    gpt_lm_loss,
    gpt_lm_loss_cp,
)
from apex_tpu.optimizers import fused_adam
from apex_tpu.transformer.moe import sync_moe_gradients
from apex_tpu.transformer.tensor_parallel import (
    allreduce_sequence_parallel_gradients,
)


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--batch", type=int, default=8, help="global batch")
    p.add_argument("--seq-len", type=int, default=512, help="global seq len")
    p.add_argument("--chunk", type=int, default=4, help="steps per jit call")
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sequence-parallel", action="store_true")
    p.add_argument(
        "--context-parallel",
        choices=["ring", "ring_zigzag", "ulysses"],
        default=None,
    )
    p.add_argument("--cp", type=int, default=2, help="cp degree when used")
    p.add_argument("--num-experts", type=int, default=0)
    p.add_argument("--data", default=None, help="packed uint16 token file")
    p.add_argument("--tiny", action="store_true")
    return p.parse_args()


def corpus(args, vocab) -> str:
    if args.data:
        return args.data
    return synthetic_token_corpus(
        os.path.join(
            tempfile.gettempdir(), f"apex_tpu_gpt_corpus_v{vocab}.bin"
        ),
        vocab_size=vocab,
        zipf_a=1.2,
        seed=1,
    )


def main():
    args = parse_args()
    cp = args.cp if args.context_parallel else 1
    cfg = GptConfig(
        **(
            dict(
                vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                intermediate_size=128, dtype=jnp.float32,
            )
            if args.tiny
            else dict(vocab_size=50304, remat=True)
        ),
        max_seq_len=args.seq_len,
        sequence_parallel=args.sequence_parallel,
        context_parallel=args.context_parallel,
        num_experts=args.num_experts,
    )
    mesh = ps.initialize_model_parallel(
        tensor_model_parallel_size=args.tp, context_parallel_size=cp
    )
    dp = ps.get_data_parallel_world_size()
    if args.steps % args.chunk:
        raise SystemExit(
            f"--steps ({args.steps}) must be a multiple of --chunk "
            f"({args.chunk}); a remainder would be silently dropped"
        )
    if args.batch % dp:
        raise SystemExit(
            f"--batch ({args.batch}) must be divisible by dp={dp}"
        )
    if args.seq_len % max(cp, 1):
        raise SystemExit(
            f"--seq-len ({args.seq_len}) must be divisible by cp={cp}"
        )
    if args.context_parallel == "ring_zigzag" and args.seq_len % (2 * cp):
        # 2 chunks per rank: a bare cp-divisible length would silently
        # truncate without this (zigzag_shard also raises at trace time)
        raise SystemExit(
            f"--seq-len ({args.seq_len}) must be divisible by 2*cp="
            f"{2 * cp} for ring_zigzag"
        )

    model = GptModel(cfg)
    tx = fused_adam(learning_rate=args.lr)
    ds = TokenFileDataset(corpus(args, cfg.vocab_size), seq_len=args.seq_len)
    loader = iter(DataLoader(ds, batch_size=args.batch, seed=7))

    def next_chunk():
        # (chunk, S, B) seq-first token batches
        return np.stack(
            [next(loader).T for _ in range(args.chunk)]
        ).astype(np.int32)

    ids0 = jnp.zeros((args.seq_len // max(cp, 1), args.batch), jnp.int32)

    def loss_fn(params, ids_local):
        if cp > 1:
            return gpt_lm_loss_cp(params, model, ids_local)
        return gpt_lm_loss(params, model, ids_local)

    def init_params(key):
        """params live inside shard_map (per-rank tp/ep shards), so init
        is its own jit call and the carry crosses chunks via donation."""
        params = model.init(key, ids0)
        params = {k: v for k, v in params.items() if k != "losses"}
        opt_state = tx.init(params)
        return params, opt_state

    def train_chunk(params, opt_state, chunk_ids):
        def body(carry, ids):
            params, opt_state = carry
            if cp > 1:
                rank = jax.lax.axis_index(ps.CONTEXT_PARALLEL_AXIS)
                s_local = ids.shape[0] // cp
                if args.context_parallel == "ring_zigzag":
                    from apex_tpu.transformer.context_parallel import (
                        zigzag_shard,
                    )

                    ids = zigzag_shard(ids, rank, cp, axis=0)
                else:
                    ids = jax.lax.dynamic_slice_in_dim(
                        ids, rank * s_local, s_local, 0
                    )
            loss, grads = jax.value_and_grad(loss_fn)(params, ids)
            if args.num_experts:
                grads = sync_moe_gradients(
                    grads,
                    sequence_parallel_axis=(
                        ps.TENSOR_PARALLEL_AXIS
                        if args.sequence_parallel and args.tp > 1
                        else None
                    ),
                )
            else:
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, ps.DATA_PARALLEL_AXIS), grads
                )
                if args.sequence_parallel and args.tp > 1:
                    grads = allreduce_sequence_parallel_gradients(grads)
            if cp > 1:
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, ps.CONTEXT_PARALLEL_AXIS),
                    grads,
                )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(jnp.add, params, updates)
            return (params, opt_state), jax.lax.pmean(
                loss, ps.DATA_PARALLEL_AXIS
            )

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), chunk_ids
        )
        return params, opt_state, losses

    batch_spec = P(None, None, ps.DATA_PARALLEL_AXIS)  # (chunk, S, B/dp)
    init_fn = jax.jit(
        jax.shard_map(
            init_params, mesh=mesh, in_specs=(P(),),
            out_specs=(P(), P()), check_vma=False,
        )
    )
    step_fn = jax.jit(
        jax.shard_map(
            train_chunk, mesh=mesh,
            in_specs=(P(), P(), batch_spec),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )

    params, opt_state = init_fn(jax.random.PRNGKey(0))
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(
        f"GPT {n_params/1e6:.0f}M params/rank | dp={dp} tp={args.tp} "
        f"cp={cp}({args.context_parallel or '-'}) "
        f"sp={args.sequence_parallel} experts={args.num_experts}"
    )
    t0 = time.perf_counter()
    losses = jnp.zeros((1,))
    for c in range(args.steps // args.chunk):
        params, opt_state, losses = step_fn(
            params, opt_state, next_chunk()
        )
        print(
            f"chunk {c}: loss "
            f"{' '.join(f'{float(l):.3f}' for l in losses)}"
        )
    jax.block_until_ready(losses)
    dt = time.perf_counter() - t0
    done = (args.steps // args.chunk) * args.chunk
    if done:
        print(f"{done} steps in {dt:.1f}s = {dt/done*1e3:.0f} ms/step")


if __name__ == "__main__":
    main()
