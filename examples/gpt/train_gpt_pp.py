"""Pipeline-parallel GPT-style LM training — the apex.transformer
pipeline workflow end to end (≙ the reference's Megatron-style pretrain
loops over ``forward_backward_pipelining_*``; SURVEY §3.5).

Demonstrates, on a virtual CPU mesh (or real chips):

- the **uniform-stage contract**: every pp rank runs the same
  ``stage_fn``; rank 0 additionally embeds (token ids ride in channel 0
  of the activation and are swapped in by a ``where`` on the first-stage
  predicate), so no per-rank Python branching exists inside the traced
  step;
- ``loss_takes_params=True``: the LAST rank computes cross-entropy
  through the **tied unembedding** (the embedding table in its own param
  tree) — Megatron's post-process pattern;
- the **embedding-grad all-reduce across pp**: rank 0's embedding grad
  and the last rank's tied-head grad are psum'd over the pp axis (≙
  Megatron's ``allreduce_embedding_grads``) so every rank's copy stays
  bit-identical through training;
- grad accumulation over microbatches inside one jitted step, 1F1B or
  interleaved (``--vpp``) schedule, fused-Adam update per stage.

Run (8 virtual devices, pp=4):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/gpt/train_gpt_pp.py --pp 4 --steps 8

Interleaved (pp=2, two chunks per rank):

    python examples/gpt/train_gpt_pp.py --pp 2 --vpp 2 --steps 8
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

if "--real-tpu" not in sys.argv:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import jax

if "--real-tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.models.bert import BertConfig, BertEncoderCore
from apex_tpu.optimizers import fused_adam
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_1f1b,
    forward_backward_pipelining_interleaved_1f1b,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
)


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--pp", type=int, default=4)
    p.add_argument("--vpp", type=int, default=0,
                   help="virtual chunks/rank (0 = non-interleaved)")
    p.add_argument("--hand-1f1b", action="store_true",
                   help="hand-scheduled 1F1B (explicit stash ring, flat "
                        "in --nm; see docs/pipeline-schedules.md) instead "
                        "of the lockstep scan; with --vpp, uses the hand "
                        "interleaved schedule (bubble (pp-1)/vpp)")
    p.add_argument("--stash", choices=["residuals", "input"],
                   default="residuals",
                   help="hand-1F1B ring contents (residuals = "
                        "no-recompute, input = minimal memory)")
    p.add_argument("--layers", type=int, default=4, help="total layers")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--nm", type=int, default=4, help="microbatches/step")
    p.add_argument("--mb", type=int, default=2, help="microbatch size")
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--real-tpu", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    pp, vpp = args.pp, args.vpp
    n_chunks = max(vpp, 1)
    if args.layers % (pp * n_chunks):
        raise SystemExit("--layers must divide pp * max(vpp, 1)")
    if vpp and args.nm % pp:
        raise SystemExit("interleaving requires --nm divisible by --pp")

    mesh = ps.initialize_model_parallel(
        pipeline_model_parallel_size=pp,
        devices=jax.devices()[:pp],
    )
    cfg = BertConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=4,
        intermediate_size=4 * args.hidden,
        max_position_embeddings=args.seq, dtype=jnp.float32,
    )
    core = BertEncoderCore(
        cfg, num_layers=args.layers // (pp * n_chunks)
    )
    tx = fused_adam(learning_rate=args.lr)
    H, V, S, MB = args.hidden, args.vocab, args.seq, args.mb

    # synthetic corpus: a fixed random LM task (next-token over a zipfy
    # stream) — enough for the loss to fall measurably in a few steps
    rng = np.random.RandomState(0)
    tokens = rng.zipf(1.5, size=200_000) % V

    def sample_batch(step):
        r = np.random.RandomState(1000 + step)
        starts = r.randint(0, len(tokens) - S - 1, size=(args.nm, MB))
        ids = np.stack(
            [[tokens[s : s + S + 1] for s in row] for row in starts]
        )  # (nm, MB, S+1)
        return jnp.asarray(ids, jnp.int32)

    def make_stage_io(ids):
        """inputs: (nm, S, MB, H) activations whose channel 0 carries the
        input token ids (rank 0 swaps in the embedding); targets: the
        shifted ids, broadcast to the activation rank for uniform stacking."""
        x = jnp.zeros((args.nm, S, MB, H), jnp.float32)
        x = x.at[..., 0].set(
            jnp.transpose(ids[..., :-1], (0, 2, 1)).astype(jnp.float32)
        )
        tgt = jnp.transpose(ids[..., 1:], (0, 2, 1))  # (nm, S, MB) int
        return x, tgt

    def stage_fn(p, x):
        # rank-gated embedding: ONLY the first virtual stage consumes ids.
        # Uniform SPMD: every rank computes both branches; `where` picks.
        # (chunk gating under interleaving rides the per-chunk is_chunk0
        # param — the schedule slices it with the rest of the chunk tree.)
        first = ps.is_pipeline_first_stage(ignore_virtual=True)
        ids = jnp.clip(x[..., 0].astype(jnp.int32), 0, V - 1)
        emb = p["embed"][ids] * jnp.sqrt(float(H))
        h = jnp.where(first & (p["is_chunk0"] > 0), emb, x)
        return core.apply(p["core"], h)

    def loss_fn(p, y, tgt):
        # tied unembedding through THIS rank's copy of the table —
        # Megatron's post-process head; grads flow into p["embed"]
        logits = jnp.einsum("sbh,vh->sbv", y, p["embed"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
        return jnp.mean(nll)

    def init_rank_params(key, chunk_index):
        core_p = core.init(
            jax.random.fold_in(key, 17 + chunk_index),
            jnp.zeros((S, MB, H)),
        )
        # embedding identical on every rank/chunk (same key, no fold)
        embed = (
            jax.random.normal(jax.random.fold_in(key, 99), (V, H))
            / np.sqrt(H)
        )
        return {
            "core": core_p, "embed": embed,
            # only virtual stage 0 embeds; other chunks pass through
            # (f32 flag so the tree stays differentiable; its grad is
            # zeroed before the optimizer and wd=0 keeps it fixed)
            "is_chunk0": jnp.asarray(float(chunk_index == 0), jnp.float32),
        }

    def train_step(params, opt_state, xs, tgts):
        if vpp and args.hand_1f1b:
            losses, grads = forward_backward_pipelining_interleaved_1f1b(
                stage_fn, loss_fn, params, (xs, tgts),
                num_microbatches=args.nm, num_model_chunks=vpp,
                loss_takes_params=True, stash=args.stash,
            )
        elif vpp:
            losses, grads = forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, params, (xs, tgts),
                num_microbatches=args.nm, num_model_chunks=vpp,
                loss_takes_params=True,
            )
        elif args.hand_1f1b:
            losses, grads = forward_backward_pipelining_1f1b(
                stage_fn, loss_fn, params, (xs, tgts),
                num_microbatches=args.nm, loss_takes_params=True,
                stash=args.stash,
            )
        else:
            losses, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, params, (xs, tgts),
                num_microbatches=args.nm, loss_takes_params=True,
            )
        # ≙ Megatron allreduce_embedding_grads: rank 0 holds the input-
        # embedding grad, the last rank the tied-head grad; psum over pp
        # keeps every copy's update identical.
        grads["embed"] = jax.lax.psum(
            grads["embed"], ps.PIPELINE_PARALLEL_AXIS
        )
        grads["is_chunk0"] = jnp.zeros_like(params["is_chunk0"])
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state, jnp.mean(losses)

    def bootstrap(key):
        rank = jax.lax.axis_index(ps.PIPELINE_PARALLEL_AXIS)
        rkey = jax.random.fold_in(key, rank)
        if vpp:
            chunks = [init_rank_params(rkey, c) for c in range(vpp)]
            params = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=0), *chunks
            )
        else:
            params = init_rank_params(rkey, 0)
        return params, tx.init(params)

    # per-leaf out specs: param/optimizer tensors are rank-local (P('pp')
    # stacks them), but optimizer SCALARS (Adam's step count) are
    # replicated — a scalar cannot carry a mesh axis.
    shape_probe = jax.eval_shape(
        lambda key: (
            lambda p: (p, tx.init(p))
        )(init_rank_params(key, 0) if not vpp else jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *[init_rank_params(key, c) for c in range(vpp)],
        )),
        jax.random.PRNGKey(0),
    )
    state_specs = jax.tree_util.tree_map(
        lambda l: P("pp") if l.ndim else P(), shape_probe
    )

    step_jit = jax.jit(
        jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(state_specs[0], state_specs[1], P(), P()),
            out_specs=(state_specs[0], state_specs[1], P()),
            check_vma=False,
        )
    )
    boot = jax.jit(
        jax.shard_map(
            bootstrap, mesh=mesh, in_specs=(P(),),
            out_specs=state_specs, check_vma=False,
        )
    )
    params, opt_state = boot(jax.random.PRNGKey(0))

    if vpp and args.hand_1f1b:
        sched = f"hand-interleaved-1F1B vpp={vpp} stash={args.stash}"
    elif vpp:
        sched = f"interleaved vpp={vpp}"
    elif args.hand_1f1b:
        sched = f"hand-1F1B stash={args.stash}"
    else:
        sched = "1F1B"
    print(f"pipeline LM: pp={pp} ({sched}), layers={args.layers}, "
          f"nm={args.nm}, mb={MB}, seq={S}")
    for step in range(args.steps):
        xs, tgts = make_stage_io(sample_batch(step))
        t0 = time.perf_counter()
        params, opt_state, loss = step_jit(params, opt_state, xs, tgts)
        loss = float(loss)
        print(f"step {step:3d}  loss {loss:7.4f}  "
              f"({(time.perf_counter() - t0) * 1e3:6.1f} ms)")
        if not np.isfinite(loss):
            raise SystemExit("non-finite loss")
    ps.destroy_model_parallel()


if __name__ == "__main__":
    main()
