"""DCGAN with amp — ≙ ``examples/dcgan/main_amp.py``: TWO models and TWO
optimizers under mixed precision, each with its own loss scaler (the
reference's ``amp.initialize([netD, netG], [optD, optG], num_losses=3``
pattern — per-loss scaling maps to per-handle states here).

Synthetic data; sized to run anywhere:

    python examples/dcgan/main_amp.py --steps 20
"""

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../.."))
)

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp


class Generator(nn.Module):
    ch: int = 32

    @nn.compact
    def __call__(self, z):
        x = nn.Dense(4 * 4 * self.ch * 4)(z).reshape(z.shape[0], 4, 4, -1)
        for mult in (4, 2, 1):
            x = nn.relu(nn.GroupNorm(num_groups=8)(x))
            x = nn.ConvTranspose(
                self.ch * mult, (4, 4), strides=(2, 2), padding="SAME"
            )(x)
        return jnp.tanh(nn.Conv(3, (3, 3), padding="SAME")(x))


class Discriminator(nn.Module):
    ch: int = 32

    @nn.compact
    def __call__(self, img):
        x = img
        for mult in (1, 2, 4):
            x = nn.Conv(
                self.ch * mult, (4, 4), strides=(2, 2), padding="SAME"
            )(x)
            x = nn.leaky_relu(x, 0.2)
        return nn.Dense(1)(x.reshape(x.shape[0], -1))[:, 0]


def bce(logits, label):
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * label + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--zdim", type=int, default=64)
    p.add_argument("--opt-level", default="O1")
    args = p.parse_args()

    gen, disc = Generator(), Discriminator()
    z0 = jnp.zeros((args.batch, args.zdim))
    img0 = jnp.zeros((args.batch, 32, 32, 3))
    g_params = gen.init(jax.random.PRNGKey(0), z0)["params"]
    d_params = disc.init(jax.random.PRNGKey(1), img0)["params"]

    txg, txd = optax.adam(2e-4, b1=0.5), optax.adam(2e-4, b1=0.5)
    # two models, two optimizers, independent scaler state each
    g_params, g_handle = amp.initialize(g_params, txg, opt_level=args.opt_level)
    d_params, d_handle = amp.initialize(d_params, txd, opt_level=args.opt_level)
    g_state, d_state = g_handle.init(g_params), d_handle.init(d_params)

    @jax.jit
    def d_step(d_params, d_state, g_params, real, z):
        fake = gen.apply({"params": g_params}, z)

        def loss(dp):
            l_real = bce(disc.apply({"params": dp}, real), 1.0)
            l_fake = bce(disc.apply({"params": dp}, jax.lax.stop_gradient(fake)), 0.0)
            return l_real + l_fake

        l, grads = jax.value_and_grad(
            lambda dp: d_handle.scale_loss(loss(dp), d_state)
        )(d_params)
        d_params, d_state, _ = d_handle.step(d_params, grads, d_state)
        return d_params, d_state, l

    @jax.jit
    def g_step(g_params, g_state, d_params, z):
        def loss(gp):
            fake = gen.apply({"params": gp}, z)
            return bce(disc.apply({"params": d_params}, fake), 1.0)

        l, grads = jax.value_and_grad(
            lambda gp: g_handle.scale_loss(loss(gp), g_state)
        )(g_params)
        g_params, g_state, _ = g_handle.step(g_params, grads, g_state)
        return g_params, g_state, l

    rng = np.random.RandomState(0)
    for i in range(args.steps):
        real = jnp.asarray(rng.randn(args.batch, 32, 32, 3), jnp.float32)
        z = jnp.asarray(rng.randn(args.batch, args.zdim), jnp.float32)
        d_params, d_state, dl = d_step(d_params, d_state, g_params, real, z)
        g_params, g_state, gl = g_step(g_params, g_state, d_params, z)
        if i % 5 == 0:
            print(f"step {i:3d}  D {float(dl):.4f}  G {float(gl):.4f}")
    print("done")


if __name__ == "__main__":
    main()
