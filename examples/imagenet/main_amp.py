"""ResNet-50 mixed-precision training — ≙ ``examples/imagenet/main_amp.py``
(``main``, ``train``, ``data_prefetcher``).

Demonstrates the full single-host recipe: ``amp.initialize`` opt levels
O0–O3, data parallelism over the mesh's ``dp`` axis (apex-DDP analog),
optional SyncBatchNorm, and a prefetching input pipeline (a background
thread stages the next batch while the device runs the current step —
the ``data_prefetcher`` side-stream analog).

Runs on any backend; with no ImageNet on disk it generates synthetic
data (shape-identical), like the reference's ``--prof`` dry runs.

    python examples/imagenet/main_amp.py --opt-level O2 --sync-bn \
        --batch-size 64 --steps 30

On CPU: APEX_TPU_FORCE_CPU=1 and an optional
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` for 8-way dp.
"""

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../.."))
)

import argparse
import queue
import threading
import time

if os.environ.get("APEX_TPU_FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, parallel_state as ps
from apex_tpu.models import resnet50
from apex_tpu.parallel import all_reduce_gradients


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O1", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--batch-size", type=int, default=64, help="global batch")
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--sync-bn", action="store_true")
    return p.parse_args()


class data_prefetcher:
    """Background-thread batch staging — ≙ main_amp.py :: data_prefetcher
    (whose CUDA side-stream becomes a host thread + async device_put)."""

    def __init__(self, it, depth: int = 2):
        self.q = queue.Queue(maxsize=depth)
        self.it = it
        self.t = threading.Thread(target=self._fill, daemon=True)
        self.t.start()

    def _fill(self):
        for batch in self.it:
            # device_put is async: the transfer overlaps the running step
            self.q.put(jax.device_put(batch))
        self.q.put(None)

    def __iter__(self):
        return self

    def __next__(self):
        item = self.q.get()
        if item is None:
            raise StopIteration
        return item


def synthetic_loader(args, steps):
    rng = np.random.RandomState(0)
    for _ in range(steps):
        x = rng.randn(
            args.batch_size, args.image_size, args.image_size, 3
        ).astype(np.float32)
        y = rng.randint(0, args.num_classes, (args.batch_size,))
        yield {"image": x, "label": y}


def main():
    args = parse_args()
    mesh = ps.initialize_model_parallel()  # all devices on the dp axis
    dp = ps.get_data_parallel_world_size()
    if args.batch_size % dp:
        raise SystemExit(f"--batch-size must be divisible by dp={dp}")

    model = resnet50(
        num_classes=args.num_classes, use_syncbn=args.sync_bn,
        dtype=jnp.bfloat16 if args.opt_level != "O0" else jnp.float32,
    )
    tx = optax.sgd(args.lr, momentum=0.9)

    x0 = jnp.zeros((2, args.image_size, args.image_size, 3))
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params, handle = amp.initialize(
        variables["params"], tx, opt_level=args.opt_level,
        loss_scale=args.loss_scale,
    )
    batch_stats = variables.get("batch_stats", {})
    amp_state = handle.init(params)

    def loss_fn(params, batch_stats, batch):
        logits, updates = model.apply(
            {"params": handle.policy.cast_to_compute(params),
             "batch_stats": batch_stats},
            batch["image"], train=True, mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.mean(
            jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)
        )
        return loss, updates["batch_stats"]

    def train_step(params, batch_stats, amp_state, batch):
        def scaled(p):
            loss, new_stats = loss_fn(p, batch_stats, batch)
            return handle.scale_loss(loss, amp_state), (loss, new_stats)

        (_, (loss, new_stats)), grads = jax.value_and_grad(
            scaled, has_aux=True
        )(params)
        grads = all_reduce_gradients(grads)
        params, amp_state2, _found_inf = handle.step(params, grads, amp_state)
        loss = jax.lax.pmean(loss, ps.DATA_PARALLEL_AXIS)
        return params, new_stats, amp_state2, loss

    sharded = jax.jit(
        jax.shard_map(
            train_step,
            mesh=mesh,
            in_specs=(P(), P(), P(), {"image": P("dp"), "label": P("dp")}),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )
    )

    loader = data_prefetcher(synthetic_loader(args, args.steps))
    t0, seen = time.perf_counter(), 0
    for i, batch in enumerate(loader):
        params, batch_stats, amp_state, loss = sharded(
            params, batch_stats, amp_state, batch
        )
        seen += args.batch_size
        if i % 10 == 0:
            scale = float(handle.state_dict(amp_state)["loss_scale"])
            print(f"step {i:4d}  loss {float(loss):.4f}  scale {scale:.0f}")
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    print(
        f"done: {seen} images in {dt:.1f}s = {seen / dt:.1f} img/s "
        f"(opt_level={args.opt_level}, dp={dp}, sync_bn={args.sync_bn})"
    )


if __name__ == "__main__":
    main()
