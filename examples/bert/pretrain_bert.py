"""BERT-Large phase-1 pretraining — the north-star recipe (BASELINE #3).

End-to-end: native-C++ masked-LM input pipeline
(:func:`apex_tpu._native.mlm_mask_batch`), BERT-Large from
:mod:`apex_tpu.models`, FusedLAMB, bf16 compute with f32 params, data
parallelism over the mesh, K steps per jitted scan chunk (minimal host
round-trips).

    python examples/bert/pretrain_bert.py --steps 24 --batch 32
    # tiny smoke on CPU:
    APEX_TPU_FORCE_CPU=1 python examples/bert/pretrain_bert.py --tiny
"""

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../.."))
)

import argparse
import time

if os.environ.get("APEX_TPU_FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu._native import NATIVE_AVAILABLE, mlm_mask_batch
from apex_tpu.models import BertConfig, BertForPreTraining, bert_pretrain_loss
from apex_tpu.optimizers import fused_lamb
from apex_tpu.parallel import all_reduce_gradients


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=24)
    p.add_argument("--batch", type=int, default=32, help="global batch")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--chunk", type=int, default=4, help="steps per jit call")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--tiny", action="store_true", help="toy config smoke run")
    return p.parse_args()


def make_batch(args, cfg, seed):
    """Host input pipeline: synthetic corpus + native MLM corruption."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(1000, cfg.vocab_size, (args.seq_len, args.batch)).astype(
        np.int32
    )
    masked, labels = mlm_mask_batch(
        ids, seed, mask_prob=0.15, mask_id=103, vocab_size=cfg.vocab_size,
        special_floor=1000,
    )
    return {
        "input_ids": jnp.asarray(masked),
        "token_type_ids": jnp.zeros((args.seq_len, args.batch), jnp.int32),
        "attention_mask": jnp.ones((args.batch, args.seq_len), jnp.int32),
        "mlm_labels": jnp.asarray(labels),
        "nsp_labels": jnp.asarray(rng.randint(0, 2, (args.batch,))),
    }


def main():
    args = parse_args()
    cfg = (
        BertConfig(
            vocab_size=2048, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=args.seq_len,
            dtype=jnp.float32,
        )
        if args.tiny
        else BertConfig(remat=True)
    )
    mesh = ps.initialize_model_parallel()
    dp = ps.get_data_parallel_world_size()
    if args.batch % dp:
        raise SystemExit(f"--batch must divide dp={dp}")

    model = BertForPreTraining(cfg)
    tx = fused_lamb(learning_rate=args.lr, weight_decay=0.01)
    batch0 = make_batch(args, cfg, 0)
    params = model.init(jax.random.PRNGKey(0), batch0["input_ids"])
    opt_state = tx.init(params)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(
        f"BERT {n_params/1e6:.0f}M params | dp={dp} | "
        f"native input pipeline: {NATIVE_AVAILABLE}"
    )

    def one_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, model, batch)
        )(params)
        grads = all_reduce_gradients(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state, jax.lax.pmean(loss, ps.DATA_PARALLEL_AXIS)

    def chunk_fn(params, opt_state, batches):
        def body(carry, batch):
            params, opt_state = carry
            params, opt_state, loss = one_step(params, opt_state, batch)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), batches
        )
        return params, opt_state, losses

    batch_specs = {
        "input_ids": P(None, None, "dp"),
        "token_type_ids": P(None, None, "dp"),
        "attention_mask": P(None, "dp"),
        "mlm_labels": P(None, None, "dp"),
        "nsp_labels": P(None, "dp"),
    }
    step = jax.jit(
        jax.shard_map(
            chunk_fn,
            mesh=mesh,
            in_specs=(P(), P(), batch_specs),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )

    t0 = time.perf_counter()
    for c in range(args.steps // args.chunk):
        batches = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[make_batch(args, cfg, c * args.chunk + i) for i in range(args.chunk)],
        )
        params, opt_state, losses = step(params, opt_state, batches)
        print(
            f"chunk {c}: loss {' '.join(f'{float(l):.3f}' for l in losses)}"
        )
    jax.block_until_ready(losses)
    dt = time.perf_counter() - t0
    steps_done = (args.steps // args.chunk) * args.chunk
    print(f"{steps_done} steps in {dt:.1f}s = {dt / steps_done * 1e3:.0f} ms/step")


if __name__ == "__main__":
    main()
