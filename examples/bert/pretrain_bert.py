"""BERT-Large phase-1 pretraining — the north-star recipe (BASELINE #3).

End-to-end over the full framework stack: packed-corpus input pipeline
(:mod:`apex_tpu.data`: memmap dataset → sharded loader → native-C++ MLM
corruption → background device prefetch), BERT-Large from
:mod:`apex_tpu.models`, FusedLAMB, bf16 compute with f32 params, data
parallelism over the mesh with K steps per jitted scan chunk, and
orbax-backed checkpoint/resume (:mod:`apex_tpu.checkpoint`).

    python examples/bert/pretrain_bert.py --steps 24 --batch 32
    # resume from the newest checkpoint:
    python examples/bert/pretrain_bert.py --ckpt-dir /tmp/ckpt --resume
    # tiny smoke on CPU:
    APEX_TPU_FORCE_CPU=1 python examples/bert/pretrain_bert.py --tiny
"""

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../.."))
)

import argparse
import tempfile
import time

if os.environ.get("APEX_TPU_FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import parallel_state as ps
from apex_tpu import _native
from apex_tpu.data import (
    DataLoader,
    DevicePrefetcher,
    TokenFileDataset,
    bert_mlm_batches,
    synthetic_token_corpus,
)
from apex_tpu.models import BertConfig, BertForPreTraining, bert_pretrain_loss
from apex_tpu.optimizers import fused_lamb
from apex_tpu.parallel import all_reduce_gradients


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=24)
    p.add_argument("--batch", type=int, default=32, help="global batch")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--chunk", type=int, default=4, help="steps per jit call")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument(
        "--data", default=None,
        help="packed token file (uint16); default: synthesize a corpus",
    )
    p.add_argument("--ckpt-dir", default=None, help="checkpoint directory")
    p.add_argument(
        "--save-every", type=int, default=8, help="checkpoint every N steps"
    )
    p.add_argument(
        "--resume", action="store_true",
        help="resume from the newest checkpoint in --ckpt-dir",
    )
    p.add_argument("--tiny", action="store_true", help="toy config smoke run")
    p.add_argument(
        "--max-predictions-per-seq", type=int, default=20,
        help="fixed-K masked-position MLM head (the reference recipe's "
        "masked_lm_* input; 0 = dense labels over all positions)",
    )
    return p.parse_args()


def corpus_path(args, cfg) -> str:
    """--data, or a synthetic zipf corpus written once to a temp file —
    either way the batches flow through the real memmap pipeline."""
    if args.data:
        return args.data
    return synthetic_token_corpus(
        os.path.join(
            tempfile.gettempdir(),
            f"apex_tpu_synth_corpus_v{cfg.vocab_size}.bin",
        ),
        vocab_size=cfg.vocab_size,
        num_tokens=2_000_000,
        floor=1000,
    )


def batch_stream(args, cfg, start_step=0):
    """chunk-stacked batch dicts: each leaf (chunk, ...) for lax.scan.

    ``start_step`` seeks the deterministic stream (O(1), index-level) so
    a resumed run continues on the batches an uninterrupted run would
    have seen — restoring params without advancing the data would
    silently retrain on already-consumed batches.
    """
    ds = TokenFileDataset(corpus_path(args, cfg), seq_len=args.seq_len)
    loader = DataLoader(ds, batch_size=args.batch, seed=1234)
    stream = bert_mlm_batches(
        loader, seed=42, mask_prob=0.15, mask_id=103,
        vocab_size=cfg.vocab_size, special_floor=1000,
        start_step=start_step,
        max_predictions_per_seq=args.max_predictions_per_seq or None,
    )
    while True:
        chunk = [next(stream) for _ in range(args.chunk)]
        if args.max_predictions_per_seq:
            # the loss reads only the packed triple — don't ship the
            # dense (S, B) labels to device alongside it
            for b in chunk:
                b.pop("mlm_labels", None)
        yield jax.tree_util.tree_map(lambda *xs: np.stack(xs), *chunk)


def main():
    args = parse_args()
    cfg = (
        BertConfig(
            vocab_size=2048, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=args.seq_len,
            dtype=jnp.float32,
        )
        if args.tiny
        else BertConfig(remat=True)
    )
    mesh = ps.initialize_model_parallel()
    dp = ps.get_data_parallel_world_size()
    if args.batch % dp:
        raise SystemExit(f"--batch must divide dp={dp}")
    if args.max_predictions_per_seq < 0:
        raise SystemExit("--max-predictions-per-seq must be >= 0")

    model = BertForPreTraining(cfg)
    tx = fused_lamb(learning_rate=args.lr, weight_decay=0.01)
    ids0 = jnp.zeros((args.seq_len, args.batch), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0)
    opt_state = tx.init(params)
    start_step = 0
    if (
        args.resume
        and args.ckpt_dir
        and ckpt.latest_step(args.ckpt_dir) is not None
    ):
        # restore replicated over the mesh (a concrete-array template
        # would re-commit every leaf to device 0 and clash with shard_map)
        rep = jax.sharding.NamedSharding(mesh, P())
        tmpl = jax.tree_util.tree_map(
            # .dtype/np.shape read metadata only — no device->host copy
            # of the (large) params/optimizer leaves (jnp.result_type
            # would also downcast the int64 step under disabled x64)
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype, sharding=rep
            ),
            ckpt.snapshot_training_state(params, opt_state, step=0),
        )
        with ckpt.CheckpointManager(args.ckpt_dir) as mgr:
            restored = mgr.restore(template=tmpl)
        params, opt_state, start_step, _, _ = ckpt.restore_training_state(
            restored
        )
        print(f"resumed from step {start_step} ({args.ckpt_dir})")
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(
        f"BERT {n_params/1e6:.0f}M params | dp={dp} | "
        f"native input pipeline: {_native.available()}"
    )

    def one_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: bert_pretrain_loss(p, model, batch)
        )(params)
        grads = all_reduce_gradients(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state, jax.lax.pmean(loss, ps.DATA_PARALLEL_AXIS)

    def chunk_fn(params, opt_state, batches):
        def body(carry, batch):
            params, opt_state = carry
            params, opt_state, loss = one_step(params, opt_state, batch)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), batches
        )
        return params, opt_state, losses

    batch_specs = {
        "input_ids": P(None, None, "dp"),
        "token_type_ids": P(None, None, "dp"),
        "attention_mask": P(None, "dp"),
        "mlm_labels": P(None, None, "dp"),
        "nsp_labels": P(None, "dp"),
    }
    if args.max_predictions_per_seq:
        # the packed triple is (chunk, K, B) — dp shards B like the labels
        # (which the stream drops in this mode; see batch_stream)
        del batch_specs["mlm_labels"]
        batch_specs.update(
            mlm_positions=P(None, None, "dp"),
            mlm_label_ids=P(None, None, "dp"),
            mlm_weights=P(None, None, "dp"),
        )
    step = jax.jit(
        jax.shard_map(
            chunk_fn,
            mesh=mesh,
            in_specs=(P(), P(), batch_specs),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )

    n_chunks = max(0, (args.steps - start_step) // args.chunk)
    if n_chunks == 0:
        print(
            f"nothing to do: resumed step {start_step} >= --steps "
            f"{args.steps} (or < one --chunk remaining)"
        )
    mgr = (
        ckpt.CheckpointManager(
            args.ckpt_dir, max_to_keep=2, save_interval_steps=args.save_every
        )
        if args.ckpt_dir
        else None
    )
    t0 = time.perf_counter()
    losses = jnp.zeros((1,))
    with DevicePrefetcher(
        batch_stream(args, cfg, start_step), depth=2
    ) as prefetch:
        for c in range(n_chunks):
            batches = next(prefetch)
            params, opt_state, losses = step(params, opt_state, batches)
            print(
                f"chunk {c}: loss "
                f"{' '.join(f'{float(l):.3f}' for l in losses)}"
            )
            if mgr is not None:
                done = start_step + (c + 1) * args.chunk
                mgr.save(
                    done,
                    ckpt.snapshot_training_state(
                        params, opt_state, step=done
                    ),
                )
    jax.block_until_ready(losses)
    if mgr is not None:
        mgr.wait_until_finished()
        print(f"checkpoints at steps {mgr.all_steps()} in {args.ckpt_dir}")
        mgr.close()
    dt = time.perf_counter() - t0
    steps_done = n_chunks * args.chunk
    if steps_done:
        print(
            f"{steps_done} steps in {dt:.1f}s = "
            f"{dt / steps_done * 1e3:.0f} ms/step"
        )


if __name__ == "__main__":
    main()
