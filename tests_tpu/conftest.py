"""On-chip kernel parity suite (VERDICT r1 item 4).

Unlike ``tests/`` (which pins the CPU backend and exercises Pallas kernels
in *interpret* mode), this directory runs against the REAL TPU backend so
the **Mosaic-compiled** kernels are what gets checked: a tiling/dtype/OOB
divergence between compiled and interpret mode surfaces here, not as a
silent numerics bug in the benchmark.

Run on a TPU host:   python -m pytest tests_tpu/ -q
On CPU every test SKIPS (visibly, not silently-passes).
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(
            reason="compiled-Pallas parity needs the real TPU backend "
            "(tests/ covers interpret mode on CPU)"
        )
        for item in items:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _restore_dispatch():
    from apex_tpu.ops import _dispatch

    yield
    _dispatch.set_use_pallas(None)
