"""On-chip kernel parity suite (VERDICT r1 item 4).

Unlike ``tests/`` (which pins the CPU backend and exercises Pallas kernels
in *interpret* mode), this directory runs against the REAL TPU backend so
the **Mosaic-compiled** kernels are what gets checked: a tiling/dtype/OOB
divergence between compiled and interpret mode surfaces here, not as a
silent numerics bug in the benchmark.

Run on a TPU host:   python -m pytest tests_tpu/ -q
On CPU every test SKIPS (visibly, not silently-passes).
"""

import threading

import jax
import pytest


def _probe_backend(timeout_s=120.0):
    """jax.default_backend(), but a wedged TPU tunnel (which hangs backend
    init indefinitely — observed in r3) degrades to 'unreachable' instead
    of hanging pytest collection forever."""
    result = []

    def probe():
        try:
            result.append(jax.default_backend())
        except Exception:
            result.append("error")

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return result[0] if result else "unreachable"


def pytest_collection_modifyitems(config, items):
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    # only mark THIS directory's items: in a combined repo-root run this
    # hook also receives tests/ items, which must keep running on CPU
    ours = [
        i for i in items
        if str(getattr(i, "fspath", "")).startswith(here)
    ]
    if not ours:
        return
    backend = _probe_backend()
    if backend != "tpu":
        skip = pytest.mark.skip(
            reason=f"compiled-Pallas parity needs the real TPU backend "
            f"(got {backend!r}; tests/ covers interpret mode on CPU)"
        )
        for item in ours:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _restore_dispatch():
    from apex_tpu.ops import _dispatch

    yield
    _dispatch.set_use_pallas(None)
