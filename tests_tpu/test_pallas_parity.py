"""Compiled Pallas kernels vs the jnp reference path, on the real chip.

Each case computes the op twice — ``set_use_pallas(True)`` (Mosaic-compiled
kernel) and ``set_use_pallas(False)`` (XLA jnp path, the correctness
reference) — on identical inputs, for forward values AND input cotangents.
≙ the reference's contrib/test pattern (CUDA kernel vs torch composition),
SURVEY §4(1).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import _dispatch
from apex_tpu.ops.attention import flash_attention, mha_reference
from apex_tpu.ops.layer_norm import (
    fused_layer_norm_affine,
    fused_rms_norm_affine,
)

# bf16 inputs, f32 kernel-internal compute on both paths: outputs agree to
# ~1e-2 absolute (bf16 rounding of the result), f32 to ~1e-5.
TOL = {jnp.bfloat16: dict(atol=2e-2, rtol=2e-2),
       jnp.float32: dict(atol=2e-5, rtol=2e-5)}


def _both_paths(fn, *args):
    # "highest" pins the XLA reference's f32 dots to true-f32 multi-pass
    # form, matching the kernels' explicit f32 HIGHEST precision — at
    # DEFAULT both sides do single-pass-bf16 mults with *different*
    # summation structure, and f32 parity would be bf16-grade.  (bf16
    # inputs are unaffected: their products are exact in f32 either way.)
    with jax.default_matmul_precision("highest"):
        _dispatch.set_use_pallas(True)
        got = jax.jit(fn)(*args)
        _dispatch.set_use_pallas(False)
        want = jax.jit(fn)(*args)
        _dispatch.set_use_pallas(None)
        return got, want


def _assert_close(got, want, dtype):
    jax.tree_util.tree_map(
        lambda g, w: np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w, np.float32),
            **TOL[dtype],
        ),
        got, want,
    )


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("memory_efficient", [False, True])
@pytest.mark.parametrize("rows,hidden", [(512, 1024), (64, 4096), (128, 768)])
def test_layer_norm_fwd_bwd(dtype, memory_efficient, rows, hidden):
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(k1, (rows, hidden), dtype)
    w = jax.random.normal(k2, (hidden,), jnp.float32) * 0.1 + 1.0
    b = jnp.linspace(-1.0, 1.0, hidden, dtype=jnp.float32)

    def f(x, w, b):
        y = fused_layer_norm_affine(
            x, w, b, (hidden,), memory_efficient=memory_efficient
        )
        return jnp.sum(y.astype(jnp.float32) ** 2)

    fn = jax.value_and_grad(f, argnums=(0, 1, 2))
    got, want = _both_paths(fn, x, w, b)
    _assert_close(got, want, dtype)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_rms_norm_fwd_bwd(dtype):
    hidden = 1024
    x = jax.random.normal(jax.random.PRNGKey(0), (256, hidden), dtype)
    w = jnp.ones((hidden,), jnp.float32)

    def f(x, w):
        y = fused_rms_norm_affine(x, w, (hidden,))
        return jnp.sum(y.astype(jnp.float32) ** 2)

    got, want = _both_paths(jax.value_and_grad(f, argnums=(0, 1)), x, w)
    _assert_close(got, want, dtype)


def _qkv(b, h, sq, sk, d, dtype):
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, sq, d), dtype)
    k = jax.random.normal(ks[1], (b, h, sk, d), dtype)
    v = jax.random.normal(ks[2], (b, h, sk, d), dtype)
    return q, k, v


def _attn_loss(attn_fn, q, k, v, bias=None, **kw):
    y = attn_fn(q, k, v, bias, **kw)
    return jnp.sum(y.astype(jnp.float32) ** 2)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize(
    "b,h,sq,sk,d,causal",
    [
        (2, 4, 256, 256, 128, False),   # lane-native head dim
        (2, 4, 256, 256, 128, True),    # causal
        (2, 4, 256, 256, 64, False),    # D=64 (padded inside the kernel)
        (1, 8, 128, 512, 128, False),   # enc-dec (Sq != Sk)
        (1, 8, 512, 256, 128, True),    # causal, bottom-right aligned
        (1, 2, 4096, 4096, 128, True),  # long context (multi-KV-block path)
    ],
)
def test_flash_attention_fwd_bwd(dtype, b, h, sq, sk, d, causal):
    q, k, v = _qkv(b, h, sq, sk, d, dtype)

    # Pallas flash kernel (forced) vs the unfused composition evaluated in
    # FULL f32 — the ground truth.  Comparing same-dtype against the bf16
    # reference would gate the kernel on the *reference's* noise: e.g. its
    # softmax-backward suffers bf16 cancellation at single-visible-key rows
    # (true gradient exactly 0, reference ~1e-1), where the kernel's
    # closed-form delta is exact.  "highest" pins the f32 dots of both
    # sides to true-f32 multi-pass MXU form.
    grad_fn = jax.value_and_grad(
        functools.partial(_attn_loss, flash_attention, causal=causal),
        argnums=(0, 1, 2),
    )
    with jax.default_matmul_precision("highest"):
        _dispatch.set_use_pallas(True)
        got = jax.jit(grad_fn)(q, k, v)
        _dispatch.set_use_pallas(None)
        want = jax.jit(
            jax.value_and_grad(
                functools.partial(_attn_loss, mha_reference, causal=causal),
                argnums=(0, 1, 2),
            )
        )(
            q.astype(jnp.float32),
            k.astype(jnp.float32),
            v.astype(jnp.float32),
        )
    # measured-on-chip error vs f32 truth across this matrix: f32 <= 4e-4
    # (causal dk worst: recompute + per-block accumulation order), bf16
    # <= 4e-2; 2.5x headroom on each
    tol = (
        dict(atol=1e-3, rtol=1e-3)
        if dtype == jnp.float32
        else dict(atol=1e-1, rtol=1e-1)
    )
    jax.tree_util.tree_map(
        lambda g, w: np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w, np.float32), **tol
        ),
        got, want,
    )


@pytest.mark.parametrize("rs", [1, None])  # key-padding row vs full rows
def test_flash_attention_bias(rs):
    """Additive key-padding bias (the (B,1,1,Sk) mask path)."""
    b, h, s, d = 2, 4, 256, 128
    dtype = jnp.bfloat16
    q, k, v = _qkv(b, h, s, s, d, dtype)
    if rs == 1:
        keep = jax.random.bernoulli(jax.random.PRNGKey(3), 0.8, (b, 1, 1, s))
    else:
        keep = jax.random.bernoulli(
            jax.random.PRNGKey(3), 0.8, (b, 1, s, s)
        )
    bias = jnp.where(keep, 0.0, -1e9).astype(jnp.float32)

    _dispatch.set_use_pallas(True)
    got = jax.jit(functools.partial(flash_attention))(q, k, v, bias)
    _dispatch.set_use_pallas(None)
    want = jax.jit(functools.partial(mha_reference))(q, k, v, bias)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=8e-2, rtol=8e-2,
    )


@pytest.mark.parametrize(
    "bias_shape",
    [
        (1, 1, 256, 256),  # G=1,  RS=Sq
        (2, 1, 256, 256),  # G=B,  RS=Sq
        (2, 4, 256, 256),  # G=BH, RS=Sq
        (1, 4, 256, 256),  # B-broadcast -> G=BH + unbroadcast sum
        (2, 1, 1, 256),    # G=B,  RS=1 (key row)
        (1, 1, 1, 256),    # G=1,  RS=1
    ],
)
@pytest.mark.parametrize("causal", [False, True])
def test_flash_dbias_on_chip(bias_shape, causal):
    """Trainable-bias backward (flash_dbias kernel) vs the f32 unfused
    reference on the real chip, across the (G, RS) group-layout matrix
    (VERDICT r2 #3)."""
    b, h, s, d = 2, 4, 256, 64
    q, k, v = _qkv(b, h, s, s, d, jnp.float32)
    bias = (
        jax.random.normal(jax.random.PRNGKey(9), bias_shape, jnp.float32)
        * 0.3
    )

    def loss(attn_fn, bias, **kw):
        return jnp.sum(attn_fn(q, k, v, bias, **kw) ** 2)

    with jax.default_matmul_precision("highest"):
        _dispatch.set_use_pallas(True)
        got = jax.jit(
            jax.grad(
                functools.partial(
                    loss, flash_attention, causal=causal, bias_grad=True
                )
            )
        )(bias)
        _dispatch.set_use_pallas(None)
        want = jax.jit(
            jax.grad(functools.partial(loss, mha_reference, causal=causal))
        )(bias)
    assert got.shape == bias.shape
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-3, rtol=1e-3
    )
    assert float(jnp.max(jnp.abs(got))) > 1e-6


@pytest.mark.parametrize(
    "sq,sk", [(100, 100), (1000, 1000), (4100, 4100), (333, 259)]
)
@pytest.mark.parametrize("causal", [False, True])
def test_flash_arbitrary_seq_on_chip(sq, sk, causal):
    """Arbitrary S on the kernel path via padding+key-masking (VERDICT r2
    #4): fwd+bwd parity at S ∈ {100, 1000, ~4k, mixed} on the real chip."""
    b, h, d = 1, 2, 64
    q, k, v = _qkv(b, h, sq, sk, d, jnp.float32)

    grad_fn = jax.value_and_grad(
        functools.partial(_attn_loss, flash_attention, causal=causal),
        argnums=(0, 1, 2),
    )
    with jax.default_matmul_precision("highest"):
        _dispatch.set_use_pallas(True)
        got = jax.jit(grad_fn)(q, k, v)
        _dispatch.set_use_pallas(None)
        want = jax.jit(
            jax.value_and_grad(
                functools.partial(_attn_loss, mha_reference, causal=causal),
                argnums=(0, 1, 2),
            )
        )(q, k, v)
    jax.tree_util.tree_map(
        lambda g, w: np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=2e-3, rtol=2e-3
        ),
        got, want,
    )


def test_scaled_softmax_compiled_matches_jnp():
    """The megatron softmax quartet is pure jnp (no Pallas kernel) but the
    custom VJP must agree with autodiff of the plain composition when
    compiled for TPU."""
    from apex_tpu.ops.scaled_softmax import scaled_masked_softmax

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 128, 128), jnp.bfloat16)
    mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.2, (2, 1, 128, 128))

    def fused(x):
        return jnp.sum(
            scaled_masked_softmax(x, mask, 0.5).astype(jnp.float32) ** 2
        )

    def ref(x):
        xs = x.astype(jnp.float32) * 0.5
        xs = jnp.where(mask, -10000.0, xs)
        y = jax.nn.softmax(xs, axis=-1)
        all_masked = jnp.all(mask, axis=-1, keepdims=True)
        y = jnp.where(all_masked, 0.0, y).astype(x.dtype)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    gv = jax.jit(jax.value_and_grad(fused))(x)
    wv = jax.jit(jax.value_and_grad(ref))(x)
    _assert_close(gv, wv, jnp.bfloat16)


def _kernel_keep_mask_full(seed, b, h, sq, sk, p):
    """Full (B,H,Sq,Sk) keep mask of the kernel's counter-based PRNG —
    `_dropout_keep_block` is a pure function of (seed, bh, absolute
    coords), so tile (0,0) at full size reproduces every kernel tile
    (identical on Mosaic and the host: pure uint32 arithmetic)."""
    from apex_tpu.ops.pallas.flash_attention import _dropout_keep_block

    return jnp.stack([
        _dropout_keep_block(seed, jnp.asarray(bh, jnp.int32), 0, 0, sq, sk, p)
        for bh in range(b * h)
    ]).reshape(b, h, sq, sk)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_on_chip(causal):
    """Compiled fused dropout vs the keep-mask golden: the Mosaic kernel
    must regenerate the identical mask the host-side hash predicts
    (values AND grads), and be deterministic across calls.  The jnp
    dispatch path draws a DIFFERENT stream by documented contract, so
    kernel-vs-jnp comparison is only valid through the shared mask."""
    from apex_tpu.ops.attention import _derive_dropout_seed, _scores

    b, h, s, d, p = 1, 2, 256, 64, 0.2
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, s, d), jnp.float32)
    rng = jax.random.PRNGKey(12)
    scale = 1.0 / (d ** 0.5)
    keep = _kernel_keep_mask_full(
        _derive_dropout_seed(rng, p)[0], b, h, s, s, p
    )

    def kernel_loss(q, k, v):
        o = flash_attention(
            q, k, v, causal=causal, dropout_p=p, dropout_rng=rng
        )
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    def golden_loss(q, k, v):
        s_ = _scores(q, k, None, causal, scale)
        probs = jax.nn.softmax(s_, axis=-1)
        pd = jnp.where(keep, probs / (1.0 - p), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    with jax.default_matmul_precision("highest"):
        _dispatch.set_use_pallas(True)
        try:
            (l_k, o_k), g_k = jax.jit(jax.value_and_grad(
                kernel_loss, argnums=(0, 1, 2), has_aux=True
            ))(q, k, v)
            (_, o_k2), _ = jax.jit(jax.value_and_grad(
                kernel_loss, argnums=(0, 1, 2), has_aux=True
            ))(q, k, v)
        finally:
            _dispatch.set_use_pallas(None)
        (l_g, o_g), g_g = jax.jit(jax.value_and_grad(
            golden_loss, argnums=(0, 1, 2), has_aux=True
        ))(q, k, v)

    np.testing.assert_array_equal(np.asarray(o_k), np.asarray(o_k2))
    np.testing.assert_allclose(
        np.asarray(o_k), np.asarray(o_g), atol=2e-5, rtol=2e-5
    )
    # Grad tolerance is PER BACKEND (ADVICE r5: one widened bound would
    # let real-TPU grad bugs below 1e-3 abs pass silently): the flash
    # backward recomputes p and groups the ds = p*(dp - delta)
    # cancellation differently from the golden einsum, and causal
    # near-diagonal rows (few visible keys, true grad ~0) amplify it —
    # measured max dev 6.9e-5 rel on v5e Mosaic (bound ~3x at 2e-4),
    # 4.8e-4 abs on CPU interpret (bound ~2x at 1e-3).  A keep-mask
    # flip would show O(|grad|)≈1e-2+ diffs, well above either atol;
    # mask identity is already pinned by the 2e-5 forward check above.
    grad_atol = 2e-4 if jax.default_backend() == "tpu" else 1e-3
    for a, b_ in zip(g_k, g_g):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=grad_atol, rtol=2e-4
        )


def test_with_lse_dropout_on_chip():
    """Compiled with-lse dropout: lse stays the undropped statistic and
    the dlse cotangent bypasses the keep mask (the ring-attention
    building block) — vs the keep-mask golden."""
    from apex_tpu.ops.attention import (
        _derive_dropout_seed,
        _scores,
        flash_attention_with_lse,
    )

    b, h, s, d, p = 1, 2, 256, 64, 0.25
    kq, kk, kv, kc = jax.random.split(jax.random.PRNGKey(21), 4)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, s, d), jnp.float32)
    dlse_w = jax.random.normal(kc, (b, h, s), jnp.float32)
    rng = jax.random.PRNGKey(22)
    scale = 1.0 / (d ** 0.5)
    keep = _kernel_keep_mask_full(
        _derive_dropout_seed(rng, p)[0], b, h, s, s, p
    )

    def kernel_loss(q, k, v):
        o, lse = flash_attention_with_lse(
            q, k, v, dropout_p=p, dropout_rng=rng
        )
        return (
            jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(lse * dlse_w),
            (o, lse),
        )

    def golden_loss(q, k, v):
        s_ = _scores(q, k, None, False, scale)
        m = jnp.max(s_, axis=-1, keepdims=True)
        pe = jnp.exp(s_ - m)
        l = jnp.sum(pe, axis=-1, keepdims=True)
        pd = jnp.where(keep, (pe / l) / (1.0 - p), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)
        lse = (m + jnp.log(l))[..., 0]
        return (
            jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(lse * dlse_w),
            (o, lse),
        )

    with jax.default_matmul_precision("highest"):
        _dispatch.set_use_pallas(True)
        try:
            (_, (o_k, lse_k)), g_k = jax.jit(jax.value_and_grad(
                kernel_loss, argnums=(0, 1, 2), has_aux=True
            ))(q, k, v)
        finally:
            _dispatch.set_use_pallas(None)
        (_, (o_g, lse_g)), g_g = jax.jit(jax.value_and_grad(
            golden_loss, argnums=(0, 1, 2), has_aux=True
        ))(q, k, v)

    np.testing.assert_allclose(
        np.asarray(o_k), np.asarray(o_g), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(lse_k), np.asarray(lse_g), atol=1e-5, rtol=1e-5
    )
    for a, b_ in zip(g_k, g_g):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


def test_sums_remat_policy_on_chip():
    """remat_policy='sums' (named saves freeing matmul epilogues, r3) must
    compile under Mosaic/XLA-TPU and reproduce the 'dots' loss and grads
    numerically on the real chip — guards against TPU-specific issues
    with save_only_these_names before the policy is benched.  Unlike the
    CPU parity test (bit-identical), the chip cannot be: the two save
    sets draw different fusion boundaries, so bf16 rounding differs
    (measured loss rel dev 4.9e-5 on v5e)."""
    from apex_tpu.models import (
        BertConfig,
        BertForPreTraining,
        bert_pretrain_loss,
    )

    kw = dict(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=8,
        intermediate_size=256, max_position_embeddings=64,
        dtype=jnp.bfloat16,
    )
    ids = jax.random.randint(jax.random.PRNGKey(1), (64, 8), 0, 512)
    batch = {
        "input_ids": ids,
        "attention_mask": jnp.ones((8, 64), jnp.int32),
        "mlm_labels": jnp.where(ids % 5 == 0, ids, -1),
        "nsp_labels": jnp.zeros((8,), jnp.int32),
    }

    def loss_and_grads(policy):
        m = BertForPreTraining(
            BertConfig(remat=True, remat_policy=policy, **kw)
        )
        params = m.init(jax.random.PRNGKey(0), ids)
        return jax.jit(
            jax.value_and_grad(lambda p: bert_pretrain_loss(p, m, batch))
        )(params)

    l_d, g_d = loss_and_grads("dots")
    l_s, g_s = loss_and_grads("sums")
    np.testing.assert_allclose(float(l_d), float(l_s), rtol=2e-4)

    # Per-leaf relative L2, not elementwise rel: this model is bf16, and
    # the two policies recompute different subgraphs, so near-zero grad
    # elements carry cancellation noise that elementwise relative error
    # amplifies without bound (measured: 6.6% rel on a 0.007-magnitude
    # element).  Worst measured leaf rel-L2: 9.7e-3 (CPU interpret) —
    # the bf16 noise floor (eps ~ 8e-3); bound at 2x.  Exact f32 parity
    # vs no-remat is pinned separately in
    # tests/test_models.py::test_remat_policy_preserves_values.
    def _leaf_rel_l2(path, a, b):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = max(float(np.linalg.norm(a)), 1e-12)
        rel = float(np.linalg.norm(a - b)) / denom
        assert rel < 2e-2, (
            f"grad leaf {jax.tree_util.keystr(path)} rel-L2 {rel:.2e}"
            f" >= 2e-2 (dots {a.ravel()[:4]}... vs sums {b.ravel()[:4]}...)"
        )

    jax.tree_util.tree_map_with_path(_leaf_rel_l2, g_d, g_s)


def test_flash_bwd_independent_dq_tiles_on_chip():
    """block_q_dq/block_k_dq (the r5 backward-tuning lever): compiled
    Mosaic results must be insensitive to the dq call's tile choice —
    dk/dv bit-identical (unchanged dkdv program), dq within f32
    accumulation-order tolerance."""
    from apex_tpu.ops.pallas import flash_attention as fa

    sq, d = 512, 64
    key = jax.random.PRNGKey(11)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (4, sq, d), jnp.bfloat16)
    k = jax.random.normal(kk, (4, sq, d), jnp.bfloat16)
    v = jax.random.normal(kv, (4, sq, d), jnp.bfloat16)
    kw = dict(scale=d ** -0.5, causal=True, block_q=256, block_k=256)
    o, lse = fa.flash_fwd(q, k, v, None, **kw)
    do = 2.0 * o
    base = fa.flash_bwd(q, k, v, o, lse, do, None, **kw)
    for bq_dq, bk_dq in ((512, 256), (128, 512)):
        alt = fa.flash_bwd(
            q, k, v, o, lse, do, None, block_q_dq=bq_dq,
            block_k_dq=bk_dq, **kw,
        )
        np.testing.assert_allclose(
            np.asarray(alt[0], np.float32), np.asarray(base[0], np.float32),
            atol=2e-2, rtol=2e-2,
        )
        for a, b in zip(alt[1:], base[1:]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# paged single-query decode attention (serving kernel, docs/serving.md)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_int8", [False, True])
def test_paged_decode_attention_on_chip(kv_int8):
    """Compiled page-walk kernel (scalar-prefetched page-table index
    maps + fused q-RoPE + optional in-kernel int8 dequant) vs the jnp
    gather reference, on the real chip.  Shapes chosen tile-aligned:
    page=128 rows x D=128 lanes, H=8 heads."""
    from apex_tpu.ops.paged_attention import (
        paged_decode_attention,
        paged_decode_attention_reference,
    )
    from apex_tpu.serve.cache import encode_kv

    b, h, d, page, pool, np_ = 2, 8, 128, 128, 8, 2
    rs = np.random.RandomState(0)
    k_pages = jnp.asarray(rs.randn(pool, h, page, d), jnp.float32)
    v_pages = jnp.asarray(rs.randn(pool, h, page, d), jnp.float32)
    q = jnp.asarray(rs.randn(b, h, d), jnp.float32)
    cos = jnp.asarray(rs.randn(b, d), jnp.float32)
    sin = jnp.asarray(rs.randn(b, d), jnp.float32)
    table = jnp.asarray([[1, 3], [5, 2]], jnp.int32)
    lengths = jnp.asarray([200, 37], jnp.int32)
    kw = dict(rope_cos=cos, rope_sin=sin)
    if kv_int8:
        k_pages, ks = encode_kv(k_pages)
        v_pages, vs = encode_kv(v_pages)
        kw.update(k_scale=ks, v_scale=vs)

    _dispatch.set_use_pallas(True)
    try:
        got = paged_decode_attention(
            q, k_pages, v_pages, table, lengths, **kw
        )
    finally:
        _dispatch.set_use_pallas(None)
    want = paged_decode_attention_reference(
        q, k_pages, v_pages, table, lengths, **kw
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
